"""Closed-loop continuous training tests (cxxnet_tpu/loop/).

Covers the feedback log's commit/rotation/CRC protocol, the eval-gated
publisher's accept/reject/rollback semantics, the HTTP ``/feedback``
route + capture mode, the model-identity observability satellites, and
the full closed loop: serve a model, append feedback, fine-tune, assert
the gate blocks a degraded update (rollback observed via the event log)
and publishes an improving one that the engine hot-reloads.
"""

import json
import os
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from cxxnet_tpu import config as cfgmod
from cxxnet_tpu import serve
from cxxnet_tpu.io.data import create_iterator
from cxxnet_tpu.loop import (
    ContinuousLoop,
    CursorFile,
    EvalGatedPublisher,
    FeedbackReader,
    FeedbackWriter,
    decode_record,
    encode_record,
    metric_improvement,
    parse_eval_metric,
)
from cxxnet_tpu.loop.feedback_log import COMMIT_SUFFIX, list_shards
from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.utils import checkpoint as ckpt
from cxxnet_tpu.utils import faults

MLP_CFG = """
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 16
  init_sigma = 0.1
layer[+1:a1] = relu:a1
layer[a1->out] = fullc:fc2
  nhidden = 4
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,16
batch_size = 32
dev = cpu
eta = 0.05
metric = error
"""


def synth_iter(nsample=256, bs=32, seed=1):
    it = create_iterator([
        ("iter", "synthetic"), ("nsample", str(nsample)),
        ("input_shape", "1,1,16"), ("nclass", "4"),
        ("batch_size", str(bs)), ("seed_data", str(seed)),
    ])
    it.init()
    return it


def synth_rows(it):
    """All (data, label) rows of a synthetic iterator's dataset."""
    rows, labs = [], []
    it.before_first()
    while it.next():
        b = it.value()
        rows.append(np.asarray(b.data).copy())
        labs.append(np.asarray(b.label).copy())
    return np.concatenate(rows), np.concatenate(labs)


def make_trained_checkpoint(tmp_path, rounds=1, seed=0):
    """Train a small MLP briefly and checkpoint it as round 1."""
    cfg = cfgmod.parse_pairs(MLP_CFG)
    tr = NetTrainer()
    tr.set_params(cfg)
    tr.set_param("seed", str(seed))
    tr.init_model()
    it = synth_iter()
    for _ in range(rounds):
        it.before_first()
        while it.next():
            b = it.value()
            tr.update_all(np.asarray(b.data), np.asarray(b.label))
    mdir = str(tmp_path / "models")
    os.makedirs(mdir, exist_ok=True)
    ckpt.write_checkpoint(
        ckpt.publish_path(mdir, 1), tr.checkpoint_bytes(),
        round_=1, net_fp=tr.net_fp(),
    )
    return cfg, mdir, tr


# ----------------------------------------------------------------------
# record codec
def test_record_roundtrip_3d_and_flat():
    img = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    rec = decode_record(encode_record(img, [2.0, 7.0]))
    np.testing.assert_array_equal(rec.data, img)
    np.testing.assert_array_equal(rec.labels, [2.0, 7.0])
    flat = decode_record(encode_record(np.arange(16, dtype=np.float32), 3))
    assert flat.data.shape == (1, 1, 16)
    np.testing.assert_array_equal(flat.labels, [3.0])
    with pytest.raises(ValueError):
        encode_record(np.zeros((2, 2)), 0)  # 2-d is ambiguous


# ----------------------------------------------------------------------
# feedback log: commit protocol
def test_uncommitted_page_invisible_until_flush(tmp_path):
    d = str(tmp_path / "log")
    w = FeedbackWriter(d, page_bytes=1 << 20)
    x = np.random.RandomState(0).randn(10, 16).astype(np.float32)
    assert w.append_batch(x, np.zeros((10, 1), np.float32)) == 10
    r = FeedbackReader(d)
    assert r.read_since(None)[0] == []  # buffered, not committed
    assert r.pending(None) == 0
    assert w.flush() == 10
    recs, cur = r.read_since(None)
    assert len(recs) == 10
    np.testing.assert_array_equal(recs[3].data.reshape(-1), x[3])
    w.close()


def test_torn_tail_and_crc_mismatch_are_skipped(tmp_path):
    d = str(tmp_path / "log")
    w = FeedbackWriter(d)
    x = np.ones((4, 16), np.float32)
    w.append_batch(x, np.zeros((4, 1), np.float32))
    w.flush()
    w.close()
    (idx, shard), = list_shards(d)
    # torn page: bytes appended with no commit entry — invisible
    with open(shard, "ab") as f:
        f.write(b"\x12garbage-torn-page")
    r = FeedbackReader(d)
    recs, cur = r.read_since(None)
    assert len(recs) == 4
    # bit rot inside a COMMITTED page: CRC catches it; page skipped,
    # counted, cursor still advances past it
    with open(shard, "r+b") as f:
        f.seek(20)
        f.write(b"\xff\xff")
    before = _counter_value("loop_feedback_bad_pages_total")
    recs, cur2 = r.read_since(None)
    assert recs == []
    assert cur2 == cur  # advanced past the bad page, not stuck
    assert _counter_value("loop_feedback_bad_pages_total") == before + 1


def test_torn_commit_sidecar_line_ignored(tmp_path):
    d = str(tmp_path / "log")
    w = FeedbackWriter(d)
    w.append_batch(np.ones((3, 16), np.float32),
                   np.zeros((3, 1), np.float32))
    w.flush()
    w.close()
    (_, shard), = list_shards(d)
    with open(shard + COMMIT_SUFFIX, "a", encoding="utf-8") as f:
        f.write('{"off": 999, "byt')  # crash mid-commit
    recs, _ = FeedbackReader(d).read_since(None)
    assert len(recs) == 3


def test_rotation_and_cross_shard_tailing(tmp_path):
    d = str(tmp_path / "log")
    # tiny pages + tiny rotation: every flush rotates
    w = FeedbackWriter(d, page_bytes=512, rotate_bytes=1024)
    x = np.random.RandomState(1).randn(40, 16).astype(np.float32)
    y = np.arange(40, dtype=np.float32)[:, None]
    w.append_batch(x, y)
    w.flush()
    shards = list_shards(d)
    assert len(shards) > 1, "rotation never happened"
    r = FeedbackReader(d)
    recs, cur = r.read_since(None)
    assert len(recs) == 40
    # record order is append order across shard boundaries
    np.testing.assert_array_equal(
        np.concatenate([rec.labels for rec in recs]), y.reshape(-1))
    # tail from a mid-stream cursor: only the new records
    w.append_batch(x[:7], y[:7])
    w.flush()
    recs2, cur2 = r.read_since(cur)
    assert len(recs2) == 7
    assert r.pending(cur2) == 0
    w.close()


def test_writer_resumes_after_reopen(tmp_path):
    d = str(tmp_path / "log")
    w = FeedbackWriter(d)
    w.append_batch(np.ones((5, 16), np.float32),
                   np.zeros((5, 1), np.float32))
    w.close()  # close() commits the partial page
    w2 = FeedbackWriter(d)
    w2.append_batch(np.ones((3, 16), np.float32) * 2,
                    np.ones((3, 1), np.float32))
    w2.flush()
    w2.close()
    recs, _ = FeedbackReader(d).read_since(None)
    assert len(recs) == 8
    assert [float(r.labels[0]) for r in recs] == [0.0] * 5 + [1.0] * 3


def test_cursor_file_roundtrip_and_corruption(tmp_path):
    cf = CursorFile(str(tmp_path / "cursor.json"))
    assert cf.load() == {"shard": 0, "off": 0}  # absent: fresh
    cf.store({"shard": 2, "off": 4096})
    assert cf.load() == {"shard": 2, "off": 4096}
    with open(cf.path, "w", encoding="utf-8") as f:
        f.write("{corrupt")
    assert cf.load() == {"shard": 0, "off": 0}  # unparseable: fresh


def _counter_value(name, **labels):
    fam = __import__("cxxnet_tpu.obs", fromlist=["registry"]).registry() \
        .snapshot().get(name, {})
    for key, v in fam.items():
        if all(f'{k}="{val}"' in key for k, val in labels.items()):
            return v
    return 0


# ----------------------------------------------------------------------
# degrade-don't-fail appends (loop.append chaos site)
def test_append_fault_drops_and_counts_instead_of_raising():
    import tempfile

    d = tempfile.mkdtemp()
    w = FeedbackWriter(d)
    faults.install("loop.append:ioerror:1:2")
    x = np.ones((1, 16), np.float32)
    y = np.zeros((1, 1), np.float32)
    assert w.append_batch(x, y) == 0  # dropped, no raise
    assert w.append_batch(x, y) == 0
    assert w.append_batch(x, y) == 1  # limit spent: accepted again
    assert w.dropped == 2
    w.flush()
    recs, _ = FeedbackReader(d).read_since(None)
    assert len(recs) == 1
    w.close()


def test_append_fault_raises_when_drop_disabled():
    import tempfile

    w = FeedbackWriter(tempfile.mkdtemp(), drop_on_error=False)
    faults.install("loop.append:ioerror:1:1")
    with pytest.raises(OSError):
        w.append(np.ones(16, np.float32), 0.0)
    w.close()


# ----------------------------------------------------------------------
# eval-gate primitives
def test_parse_eval_metric_prefers_section_prefix():
    line = "\ttrain-error:0\teval-error:0.25\teval-logloss:1.5"
    assert parse_eval_metric(line, prefix="eval-") == ("eval-error", 0.25)
    assert parse_eval_metric(line, "logloss", prefix="eval-") == (
        "eval-logloss", 1.5)
    with pytest.raises(ValueError):
        parse_eval_metric("\ttrain-error:0", prefix="eval-")
    with pytest.raises(ValueError):
        parse_eval_metric("", prefix="eval-")


def test_metric_improvement_orientation():
    # error/rmse/logloss: down is better
    assert metric_improvement("eval-error", 0.5, 0.3) == pytest.approx(0.2)
    assert metric_improvement("eval-logloss[f]", 1.0, 1.2) == pytest.approx(-0.2)
    # rec@n: up is better
    assert metric_improvement("eval-rec@5", 0.5, 0.7) == pytest.approx(0.2)


# ----------------------------------------------------------------------
# publish pointer
def test_publish_pointer_roundtrip(tmp_path):
    d = str(tmp_path)
    assert ckpt.read_publish_pointer(d) is None
    ckpt.write_publish_pointer(d, 3, ckpt.publish_path(d, 3),
                               net_fp="abcd1234",
                               metric={"name": "eval-error", "value": 0.1},
                               prev_round=2)
    ptr = ckpt.read_publish_pointer(d)
    assert ptr["round"] == 3 and ptr["prev"]["round"] == 2
    assert ptr["metric"]["value"] == 0.1


# ----------------------------------------------------------------------
# the closed loop
def test_closed_loop_gate_blocks_worse_publishes_better(tmp_path):
    """Serve → poisoned feedback rejected (rollback in the event log) →
    correct feedback published → engine hot-reloads the new weights
    fingerprint."""
    cfg, mdir, _ = make_trained_checkpoint(tmp_path)
    eng = serve.Engine(cfg=cfg, model_dir=mdir, max_batch_size=32)
    try:
        assert eng.round == 1
        crc0 = eng.model_crc32
        assert crc0 is not None
        fdir = str(tmp_path / "feedback")
        w = FeedbackWriter(fdir)
        base, ev = synth_iter(), synth_iter()
        loop = ContinuousLoop(
            eng, cfg, feedback_dir=fdir, base_iter=base, eval_iter=ev,
            rounds_per_cycle=2, replay_ratio=0.25, min_records=64,
            feedback_writer=w, silent=True,
        )
        assert loop.publisher.serving_metric is not None
        X, Y = synth_rows(synth_iter())
        # below min_records: idle, nothing trains
        w.append_batch(X[:10], Y[:10])
        assert loop.run_cycle() == "idle"
        # poisoned labels: candidate degrades -> gate rejects, engine
        # keeps serving round 1, trainer rolls back
        w.append_batch(X[:200], (Y[:200] + 1.0) % 4)
        assert loop.run_cycle() == "rejected"
        assert eng.round == 1 and eng.model_crc32 == crc0
        from cxxnet_tpu.obs import recent

        kinds = [e["kind"] for e in recent(20)]
        assert "loop.reject" in kinds and "loop.rollback" in kinds
        # correct labels: candidate improves -> published + hot-reloaded
        w.append_batch(X, Y)
        assert loop.run_cycle() == "published"
        assert eng.round == 2
        assert eng.model_crc32 != crc0  # new weights fingerprint serves
        ptr = ckpt.read_publish_pointer(mdir)
        assert ptr["round"] == 2 and ptr["prev"]["round"] == 1
        assert [e["kind"] for e in recent(5)][-1] == "loop.cycle"
        # the published metric becomes the next gate's bar
        assert loop.publisher.serving_metric == pytest.approx(
            ptr["metric"]["value"])
        # cursor consumed everything: an empty cycle is idle
        assert loop.run_cycle() == "idle"
        w.close()
    finally:
        eng.close()


def test_all_bad_pages_consume_cursor_instead_of_stalling(tmp_path):
    """When every committed page past the cursor fails its CRC, the
    idle cycle still consumes them — otherwise pending() keeps
    promising work and every cycle re-reads and re-counts the same rot
    forever."""
    cfg, mdir, _ = make_trained_checkpoint(tmp_path)
    eng = serve.Engine(cfg=cfg, model_dir=mdir, max_batch_size=32)
    try:
        fdir = str(tmp_path / "feedback")
        w = FeedbackWriter(fdir)
        X, Y = synth_rows(synth_iter())
        w.append_batch(X[:80], Y[:80])
        w.flush()
        (_, shard), = list_shards(fdir)
        with open(shard, "r+b") as f:  # rot every committed page
            f.seek(30)
            f.write(b"\xff\xff\xff")
        loop = ContinuousLoop(
            eng, cfg, feedback_dir=fdir, base_iter=synth_iter(),
            eval_iter=synth_iter(), min_records=64,
            feedback_writer=w, silent=True,
        )
        before = _counter_value("loop_feedback_bad_pages_total")
        assert loop.run_cycle() == "idle"
        assert _counter_value("loop_feedback_bad_pages_total") == before + 1
        assert FeedbackReader(fdir).pending(loop.cursor_file.load()) == 0
        # the rot is consumed: later cycles do not re-count it
        assert loop.run_cycle() == "idle"
        assert _counter_value("loop_feedback_bad_pages_total") == before + 1
        w.close()
    finally:
        eng.close()


def test_rejected_cycle_still_advances_cursor(tmp_path):
    """Poisoned records are consumed, not retried forever: after a
    reject the same records do not re-train the next cycle."""
    cfg, mdir, _ = make_trained_checkpoint(tmp_path)
    eng = serve.Engine(cfg=cfg, model_dir=mdir, max_batch_size=32)
    try:
        fdir = str(tmp_path / "feedback")
        w = FeedbackWriter(fdir)
        loop = ContinuousLoop(
            eng, cfg, feedback_dir=fdir, base_iter=synth_iter(),
            eval_iter=synth_iter(), rounds_per_cycle=1, min_records=32,
            feedback_writer=w, silent=True,
        )
        X, Y = synth_rows(synth_iter())
        w.append_batch(X[:64], (Y[:64] + 1.0) % 4)
        assert loop.run_cycle() == "rejected"
        assert loop.run_cycle() == "idle"
        w.close()
    finally:
        eng.close()


# ----------------------------------------------------------------------
# HTTP front-end: /feedback + capture + identity satellites
def _get(port, path, raw=False):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as r:
        body = r.read()
    return body.decode() if raw else json.loads(body)


def _post(port, path, obj):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(obj).encode("utf-8"),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read())


def test_http_feedback_route_and_identity(tmp_path):
    cfg, mdir, _ = make_trained_checkpoint(tmp_path)
    eng = serve.Engine(cfg=cfg, model_dir=mdir, max_batch_size=32,
                       batch_timeout_ms=1)
    fdir = str(tmp_path / "feedback")
    w = FeedbackWriter(fdir)
    httpd = serve.make_server(eng, port=0, feedback=w,
                              capture_predict=True)
    port = httpd.server_port
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    x = np.random.RandomState(0).randn(6, 16).astype(np.float32)
    try:
        out = _post(port, "/feedback",
                    {"data": x.tolist(), "label": [0, 1, 2, 3, 0, 1]})
        assert out["appended"] == 6 and out["dropped"] == 0
        # lineage: the response names the durable id range the records
        # got, and a correlation id ties the request to server events
        assert out["seq"] == [0, 5]
        assert isinstance(out["rid"], str) and out["rid"]
        # label/data mismatch is a 400, not a drop
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(port, "/feedback", {"data": x.tolist(), "label": [1]})
        assert e.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(port, "/feedback", {"data": x.tolist()})
        assert e.value.code == 400
        # capture mode: a successful /predict logs inputs + predictions
        pred = _post(port, "/predict", {"data": x[:3].tolist()})["pred"]
        w.flush()
        recs, _ = FeedbackReader(fdir).read_since(None)
        assert len(recs) == 9  # 6 feedback + 3 captured
        np.testing.assert_array_equal(
            [float(r.labels[0]) for r in recs[6:]], pred)
        # identity satellites: /healthz + /statsz carry the weights
        # fingerprint, /metricsz gauges it
        h = _get(port, "/healthz")
        assert h["model_crc32"] == eng.model_crc32
        st = _get(port, "/statsz")
        assert st["model"]["crc32"] == eng.model_crc32
        assert st["model"]["round"] == 1
        mez = _get(port, "/metricsz", raw=True)
        assert "serve_model_round 1" in mez
        assert f"serve_model_crc32 {eng.model_crc32}" in mez
    finally:
        httpd.shutdown()
        httpd.server_close()
        w.close()
        eng.close()


def test_feedback_route_404_when_unarmed():
    from test_serve import make_trainer

    eng = serve.Engine(trainer=make_trainer(), max_batch_size=8,
                       batch_timeout_ms=0)
    httpd = serve.make_server(eng, port=0)
    port = httpd.server_port
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(port, "/feedback",
                  {"data": [[0.0] * 16], "label": [1]})
        assert e.value.code == 404
    finally:
        httpd.shutdown()
        httpd.server_close()
        eng.close()


# ----------------------------------------------------------------------
# lineage: request -> feedback seq ids -> publish pointer -> resolution
def test_feedback_seq_ids_durable_across_reopen(tmp_path):
    d = str(tmp_path / "log")
    x = np.random.RandomState(0).randn(8, 16).astype(np.float32)
    w = FeedbackWriter(d)
    n, first, last = w.append_batch_ids(x[:5], np.arange(5.0))
    assert (n, first, last) == (5, 0, 4)
    w.close()  # close commits the buffered page
    # the commit sidecar anchors the page's id range
    (_, shard), = list_shards(d)
    ent = json.loads(open(shard + COMMIT_SUFFIX).read().splitlines()[0])
    assert ent["seq0"] == 0 and ent["nrec"] == 5
    # a reopened writer resumes PAST everything ever assigned
    w2 = FeedbackWriter(d)
    n, first, last = w2.append_batch_ids(x[5:], np.arange(3.0))
    assert (n, first, last) == (3, 5, 7)
    w2.close()
    # the reader hands each record its id back
    recs, _ = FeedbackReader(d).read_since(None)
    assert [r.seq for r in recs] == list(range(8))


def test_closed_loop_publish_carries_resolvable_lineage(tmp_path):
    """The acceptance chain: poisoned records are consumed but must NOT
    appear in the published lineage (their effect was rolled back); the
    publishing cycle's id range lands in PUBLISHED.json and resolves
    back to committed feedback pages via tools/obs_dump.py."""
    cfg, mdir, _ = make_trained_checkpoint(tmp_path)
    eng = serve.Engine(cfg=cfg, model_dir=mdir, max_batch_size=32)
    try:
        fdir = str(tmp_path / "feedback")
        w = FeedbackWriter(fdir)
        loop = ContinuousLoop(
            eng, cfg, feedback_dir=fdir, base_iter=synth_iter(),
            eval_iter=synth_iter(), rounds_per_cycle=2, min_records=64,
            feedback_writer=w, silent=True,
        )
        X, Y = synth_rows(synth_iter())
        # phase A: poisoned -> rejected; ids 0..199 are spent
        w.append_batch(X[:200], (Y[:200] + 1.0) % 4)
        assert loop.run_cycle() == "rejected"
        # phase B: correct -> published; ids 200..455 trained the model
        w.append_batch(X, Y)
        assert loop.run_cycle() == "published"
        ptr = ckpt.read_publish_pointer(mdir)
        lin = ptr["lineage"]
        assert lin == {"first_seq": 200, "last_seq": 455,
                       "records": 256, "cycles": 1}
        # resolution end to end (what --lineage runs)
        import os as _os
        import sys as _sys

        _sys.path.insert(0, _os.path.join(
            _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))),
            "tools"))
        import obs_dump

        report, problems = obs_dump.resolve_lineage(mdir, fdir)
        assert problems == []
        assert report["lineage"] == lin
        assert report["round"] == ptr["round"]
        res = report["resolved"]
        assert res["records_in_range"] == 256
        assert all(p["seq"][0] >= 0 for p in res["pages"])
        w.close()
    finally:
        eng.close()


def test_lineage_resolution_fails_loud_without_pointer(tmp_path):
    import os as _os
    import sys as _sys

    _sys.path.insert(0, _os.path.join(
        _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))),
        "tools"))
    import obs_dump

    _report, problems = obs_dump.resolve_lineage(str(tmp_path))
    assert problems and "cannot read" in problems[0]
    # a pointer written outside the loop (no lineage block) is reported
    ckpt.write_publish_pointer(str(tmp_path), 1, "0001.model")
    report, problems = obs_dump.resolve_lineage(str(tmp_path))
    assert report["lineage"] is None
    assert problems and "no lineage block" in problems[0]


def test_concurrent_feedback_batches_get_disjoint_contiguous_ranges(tmp_path):
    """Concurrent /feedback handlers must each get an id range covering
    exactly their own records — the whole batch is appended under one
    lock hold, so ranges are contiguous and never interleave."""
    d = str(tmp_path / "log")
    w = FeedbackWriter(d)
    x = np.random.RandomState(0).randn(20, 16).astype(np.float32)
    ranges = []
    lock = threading.Lock()

    def poster():
        for _ in range(10):
            out = w.append_batch_ids(x, np.zeros((20, 1), np.float32))
            with lock:
                ranges.append(out)

    threads = [threading.Thread(target=poster) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(ranges) == 40
    spans = []
    for n, first, last in ranges:
        assert n == 20
        assert last - first + 1 == 20  # contiguous: only OUR records
        spans.append((first, last))
    spans.sort()
    for (_, a_last), (b_first, _) in zip(spans, spans[1:]):
        assert b_first == a_last + 1  # disjoint, gap-free total order
    w.close()


def test_acknowledged_seq_ids_never_reused_after_crash(tmp_path):
    """Ids handed to /feedback clients for records still BUFFERED at a
    crash must never be reassigned: assignment draws from durably
    reserved blocks, so a crashed writer's successor starts past the
    reservation (a gap), while a cleanly closed writer resumes exactly."""
    d = str(tmp_path / "log")
    x = np.ones((3, 16), np.float32)
    y = np.zeros((3, 1), np.float32)
    w = FeedbackWriter(d)
    w.append_batch_ids(x, y)      # seqs 0-2
    w.flush()                     # committed: pages cover through 2
    _, first, last = w.append_batch_ids(x, y)  # seqs 3-5, buffered only
    assert (first, last) == (3, 5)
    # simulate a crash: no close(), the buffered page never commits
    w._f.close()
    w2 = FeedbackWriter(d)
    _, first2, _ = w2.append_batch_ids(x, y)
    assert first2 > 5  # acknowledged ids 3-5 are a gap, never reused
    w2.close()
    # clean close shrinks the reservation: the next reopen is gap-free
    w3 = FeedbackWriter(d)
    _, first3, _ = w3.append_batch_ids(x, y)
    assert first3 == first2 + 3
    w3.close()


# ----------------------------------------------------------------------
# multi-tenant config grammar ([tenant:<name>] sections)
def test_split_tenant_sections_basic_and_errors():
    base = [("eta", "0.1"), ("batch_size", "8")]
    cfg = base + [
        ("tenant", "alpha"), ("model_dir", "ma"), ("eta", "0.2"),
        ("tenant", "end"),
        ("tenant", "beta"), ("model_dir", "mb"), ("tenant", "end"),
        ("seed", "1"),
    ]
    rest, tenants = cfgmod.split_tenant_sections(cfg)
    assert rest == base + [("seed", "1")]
    assert [t.name for t in tenants] == ["alpha", "beta"]
    assert tenants[0].entries == [("model_dir", "ma"), ("eta", "0.2")]
    # the effective per-tenant stream resolves by last-entry-wins
    eff = rest + tenants[0].entries
    assert cfgmod.cfg_get(eff, "eta") == "0.2"
    assert cfgmod.cfg_get(rest + tenants[1].entries, "eta") == "0.1"
    with pytest.raises(cfgmod.ConfigError):  # unclosed section
        cfgmod.split_tenant_sections([("tenant", "a"), ("x", "1")])
    with pytest.raises(cfgmod.ConfigError):  # end without open
        cfgmod.split_tenant_sections([("tenant", "end")])
    with pytest.raises(cfgmod.ConfigError):  # nested open
        cfgmod.split_tenant_sections(
            [("tenant", "a"), ("tenant", "b")])
    with pytest.raises(cfgmod.ConfigError):  # duplicate name
        cfgmod.split_tenant_sections(
            [("tenant", "a"), ("tenant", "end"),
             ("tenant", "a"), ("tenant", "end")])
    with pytest.raises(cfgmod.ConfigError):  # section opener inside
        cfgmod.split_tenant_sections(
            [("tenant", "a"), ("data", "start"), ("tenant", "end")])


def test_cli_set_param_passes_tenant_sections_through():
    """A tenant's model_dir must never clobber the driver's globals —
    the CLI defers everything inside tenant blocks to loop/tenant.py."""
    from cxxnet_tpu.cli import LearnTask

    t = LearnTask()
    t.set_param("model_dir", "driver_models")
    t.set_param("tenant", "a")
    t.set_param("model_dir", "tenant_models")
    t.set_param("task", "loop_fleet")  # inside section: NOT the driver's
    t.set_param("tenant", "end")
    t.set_param("seed", "3")
    assert t.name_model_dir == "driver_models"
    assert t.task != "loop_fleet"
    _, tenants = cfgmod.split_tenant_sections(t.cfg)
    assert tenants and tenants[0].entries[0] == ("model_dir",
                                                "tenant_models")


# ----------------------------------------------------------------------
# per-slice cohort gate
def test_accumulate_cohort_counts_and_accuracy():
    from cxxnet_tpu.loop.publisher import (accumulate_cohort_counts,
                                           cohort_accuracy)

    counts = {}
    preds = np.array([0, 1, 1, 0], np.float32)
    labels = np.array([[0, 7], [1, 7], [0, 9], [0, 9]], np.float32)
    accumulate_cohort_counts(counts, preds, labels, source_field=1)
    assert counts["class:0"] == [2, 3]  # rows 0,2,3: hits 0 and 3
    assert counts["class:1"] == [1, 1]
    assert counts["source:7"] == [2, 2]
    assert counts["source:9"] == [1, 2]
    acc = cohort_accuracy(counts, min_count=2)
    assert acc["class:0"] == pytest.approx(2 / 3)
    assert "class:1" not in acc  # below min_count: dropped
    assert acc["source:9"] == pytest.approx(0.5)


def test_slice_gate_rejects_naming_worst_cohort(tmp_path):
    """A candidate that improves the aggregate but sacrifices one
    cohort beyond publish_slice_floor is rejected, and the reject
    event names the cohort and carries the cycle's lineage."""
    cfg, mdir, tr = make_trained_checkpoint(tmp_path)
    eng = serve.Engine(cfg=cfg, model_dir=mdir, max_batch_size=32)
    try:
        pub = EvalGatedPublisher(eng, synth_iter(), slice_floor=0.05,
                                 slice_min_count=1)
        pub.evaluate = lambda trainer: ("eval-error", 0.30)
        pub.evaluate_cohorts = lambda trainer: {
            "class:0": 0.9, "class:1": 0.8, "class:2": 0.7}
        pub.record_serving_baseline(tr)
        assert pub.serving_cohorts == {
            "class:0": 0.9, "class:1": 0.8, "class:2": 0.7}
        # candidate: aggregate improves, class:1 collapses, class:2
        # dips within the floor
        pub.evaluate = lambda trainer: ("eval-error", 0.10)
        pub.evaluate_cohorts = lambda trainer: {
            "class:0": 0.95, "class:1": 0.60, "class:2": 0.66}
        lin = {"first_seq": 10, "last_seq": 42, "records": 33,
               "cycles": 1}
        assert pub.consider(tr, cycle=7, lineage=lin) is False
        from cxxnet_tpu.obs import recent

        ev = [e for e in recent(10) if e["kind"] == "loop.reject"][-1]
        assert ev["cohort"] == "class:1"
        assert "class:1" in ev["reason"]
        assert "publish_slice_floor" in ev["reason"]
        assert ev["lineage"] == lin  # regression attributable to seqs
        assert eng.round == 1  # nothing published
        # same aggregate, cohorts all within the floor -> publishes
        pub.evaluate_cohorts = lambda trainer: {
            "class:0": 0.95, "class:1": 0.79, "class:2": 0.70}
        assert pub.consider(tr, cycle=8, lineage=lin) is True
        assert eng.round == 2
        # published cohort vector becomes the next bar, and persists
        ptr = ckpt.read_publish_pointer(mdir)
        assert ptr["metric"]["cohorts"]["class:1"] == pytest.approx(0.79)
        assert pub.serving_cohorts["class:1"] == pytest.approx(0.79)
    finally:
        eng.close()


def test_cohort_too_small_in_candidate_is_not_gated(tmp_path):
    """A cohort that shrank below slice_min_count in the candidate eval
    cannot be compared -- the gate skips it instead of inventing a
    regression."""
    cfg, mdir, tr = make_trained_checkpoint(tmp_path)
    eng = serve.Engine(cfg=cfg, model_dir=mdir, max_batch_size=32)
    try:
        pub = EvalGatedPublisher(eng, synth_iter(), slice_floor=0.01,
                                 slice_min_count=1)
        pub.evaluate = lambda trainer: ("eval-error", 0.30)
        pub.evaluate_cohorts = lambda trainer: {"class:0": 0.9,
                                                "class:1": 0.8}
        pub.record_serving_baseline(tr)
        pub.evaluate = lambda trainer: ("eval-error", 0.10)
        pub.evaluate_cohorts = lambda trainer: {"class:0": 0.95}
        assert pub.consider(tr) is True  # class:1 absent: not gated
    finally:
        eng.close()


# ----------------------------------------------------------------------
# baseline persistence (no re-baselining on restart)
def test_serving_baseline_recorded_not_reevaluated_on_restart(tmp_path):
    cfg, mdir, tr = make_trained_checkpoint(tmp_path)
    eng = serve.Engine(cfg=cfg, model_dir=mdir, max_batch_size=32)
    try:
        pub = EvalGatedPublisher(eng, synth_iter())
        bar = pub.record_serving_baseline(tr)
        ptr = ckpt.read_publish_pointer(mdir)
        assert ptr["round"] == 1  # first boot persisted the bar
        assert ptr["metric"]["value"] == pytest.approx(bar)
        # a restarted publisher reads the recorded bar back: with no
        # publish_metric configured, one eval validates the metric NAME
        # but the VALUE bar must stay recorded (re-baselining reset the
        # bar every bounce)
        pub2 = EvalGatedPublisher(eng, synth_iter())
        pub2.evaluate = lambda trainer: (ptr["metric"]["name"], 0.99)
        assert pub2.record_serving_baseline(tr) == pytest.approx(bar)
        assert pub2.serving_metric_name == ptr["metric"]["name"]
        from cxxnet_tpu.obs import recent

        ev = [e for e in recent(5) if e["kind"] == "loop.baseline"][-1]
        assert ev["source"] == "recorded"
        # with publish_metric pinned, the substring check suffices and
        # NO eval runs at all on restart
        pub3 = EvalGatedPublisher(eng, synth_iter(),
                                  metric_name="error")

        def boom(trainer):
            raise AssertionError("pinned metric must not re-evaluate")

        pub3.evaluate = boom
        assert pub3.record_serving_baseline(tr) == pytest.approx(bar)
        # the eval conf changed between restarts (metric renamed): the
        # recorded bar is for a DIFFERENT metric -> fresh re-baseline,
        # never a cross-metric comparison
        pub4 = EvalGatedPublisher(eng, synth_iter())
        pub4.evaluate = lambda trainer: ("eval-rec@1", 0.7)
        assert pub4.record_serving_baseline(tr) == pytest.approx(0.7)
        assert pub4.serving_metric_name == "eval-rec@1"
        ev = [e for e in recent(5) if e["kind"] == "loop.baseline"][-1]
        assert ev["source"] == "evaluated"
    finally:
        eng.close()


def test_slice_baseline_vector_persists_across_restart(tmp_path):
    """The cohort vector gates against the RECORDED serving bar after a
    restart; a pre-slice-gating pointer is grown the vector once."""
    cfg, mdir, tr = make_trained_checkpoint(tmp_path)
    eng = serve.Engine(cfg=cfg, model_dir=mdir, max_batch_size=32)
    try:
        # legacy pointer: recorded metric but no cohort vector
        ckpt.write_publish_pointer(
            mdir, 1, eng.model_path, net_fp=tr.net_fp(),
            metric={"name": "eval-error", "value": 0.25})
        pub = EvalGatedPublisher(eng, synth_iter(), slice_floor=0.05,
                                 slice_min_count=1)
        pub.evaluate_cohorts = lambda trainer: {"class:0": 0.75}
        assert pub.record_serving_baseline(tr) == pytest.approx(0.25)
        ptr = ckpt.read_publish_pointer(mdir)
        assert ptr["metric"]["cohorts"] == {"class:0": 0.75}
        # restart: vector comes back recorded, no cohort re-eval (the
        # scalar eval still runs once to validate the metric name)
        pub2 = EvalGatedPublisher(eng, synth_iter(), slice_floor=0.05,
                                  slice_min_count=1)

        def boom(trainer):
            raise AssertionError("recorded vector must be read back")

        pub2.evaluate = lambda trainer: ("eval-error", 0.5)
        pub2.evaluate_cohorts = boom
        assert pub2.record_serving_baseline(tr) == pytest.approx(0.25)
        assert pub2.serving_cohorts == {"class:0": 0.75}
    finally:
        eng.close()


# ----------------------------------------------------------------------
# retention: compaction of consumed shards (loop/retention.py)
def _rotated_log(tmp_path, n=60):
    """A feedback log forced into many small shards, fully committed."""
    d = str(tmp_path / "log")
    w = FeedbackWriter(d, page_bytes=256, rotate_bytes=512)
    X = np.random.RandomState(0).randn(n, 16).astype(np.float32)
    w.append_batch_ids(X, np.arange(n, dtype=np.float32)[:, None])
    w.flush()
    return d, w


def test_retention_compacts_consumed_shards(tmp_path):
    from cxxnet_tpu.loop.feedback_log import read_retention
    from cxxnet_tpu.loop.retention import RetentionOptions, Sweeper

    d, w = _rotated_log(tmp_path)
    shards0 = list_shards(d)
    assert len(shards0) > 3, "rotation never happened"
    bytes0 = sum(os.path.getsize(p) for _, p in shards0)
    recs, cur = FeedbackReader(d).read_since(None)
    assert len(recs) == 60
    sw = Sweeper(d, RetentionOptions(0, 0), tenant="t0")
    out = sw.sweep(cur)
    assert out["deleted_shards"] >= 3
    assert out["compacted_below"] == cur["shard"]
    assert read_retention(d)["compacted_below"] == cur["shard"]
    left = list_shards(d)
    assert all(idx >= cur["shard"] for idx, _ in left)
    assert out["disk_bytes"] < bytes0
    # the consumed-up-to cursor still works; new appends still commit
    # and read back CRC-verified
    r = FeedbackReader(d)
    assert r.pending(cur) == 0
    w.append_batch(np.ones((4, 16), np.float32),
                   np.zeros((4, 1), np.float32))
    w.flush()
    recs2, _ = r.read_since(cur)
    assert len(recs2) == 4
    assert _counter_value("loop_compactions_total", tenant="t0") >= 1
    w.close()


def test_retention_never_deletes_pending_lineage_or_unconsumed(tmp_path):
    from cxxnet_tpu.loop.retention import (RetentionOptions, Sweeper,
                                           safe_boundary)

    d, w = _rotated_log(tmp_path)
    nshards = len(list_shards(d))
    _, cur = FeedbackReader(d).read_since(None)
    sw = Sweeper(d, RetentionOptions(0, 0))
    # an in-flight cycle is still training on seq 0 (shard 0): even a
    # fully-advanced cursor must not free its shard
    assert safe_boundary(d, cur, pending_first_seq=0) == 0
    out = sw.sweep(cur, pending_first_seq=0)
    assert out["deleted_shards"] == 0
    assert len(list_shards(d)) == nshards
    # a cursor that consumed nothing frees nothing (the live tail and
    # every unconsumed shard are above it)
    out = sw.sweep({"shard": 0, "off": 0})
    assert out["deleted_shards"] == 0
    # a pending id that cannot be located freezes the boundary at 0
    assert safe_boundary(d, cur, pending_first_seq=10 ** 9) == 0
    w.close()


def test_retention_retain_shards_and_bytes_bounds(tmp_path):
    from cxxnet_tpu.loop.retention import RetentionOptions, Sweeper

    d, w = _rotated_log(tmp_path)
    _, cur = FeedbackReader(d).read_since(None)
    consumed = [idx for idx, _ in list_shards(d) if idx < cur["shard"]]
    # retain_bytes larger than the log: nothing deleted even though
    # every candidate is consumed
    out = Sweeper(d, RetentionOptions(0, 1 << 30)).sweep(cur)
    assert out["deleted_shards"] == 0
    # keep the newest 2 consumed shards as the operator re-read hedge
    out = Sweeper(d, RetentionOptions(2, 0)).sweep(cur)
    assert out["deleted_shards"] == len(consumed) - 2
    kept = [idx for idx, _ in list_shards(d)]
    assert consumed[-2] in kept and consumed[-1] in kept
    w.close()


def test_stale_cursor_into_compacted_shard_fails_loud(tmp_path):
    from cxxnet_tpu.loop import StaleCursorError
    from cxxnet_tpu.loop.retention import RetentionOptions, Sweeper

    d, w = _rotated_log(tmp_path)
    _, cur = FeedbackReader(d).read_since(None)
    Sweeper(d, RetentionOptions(0, 0)).sweep(cur)
    r = FeedbackReader(d)
    stale = {"shard": 0, "off": 0}
    with pytest.raises(StaleCursorError) as e:
        r.read_since(stale)
    assert e.value.compacted_below == cur["shard"]
    assert e.value.cursor == stale
    with pytest.raises(StaleCursorError):
        r.pending(stale)
    w.close()


def test_retention_crash_between_pointer_and_unlink_is_safe(tmp_path):
    """kill -9 after the boundary fsync but before the unlinks: the
    orphans below the boundary are invisible to readers, every record
    above it stays CRC-readable, and the next sweep deletes them."""
    import json as _json

    from cxxnet_tpu.loop.feedback_log import RETENTION_FILE
    from cxxnet_tpu.loop.retention import RetentionOptions, Sweeper

    d, w = _rotated_log(tmp_path)
    _, cur = FeedbackReader(d).read_since(None)
    boundary = cur["shard"]
    assert boundary >= 2
    # the crash: pointer durable, files still on disk
    with open(os.path.join(d, RETENTION_FILE), "w") as f:
        _json.dump({"compacted_below": boundary}, f)
    n_files = len(list_shards(d))
    # records above the boundary read back CRC-verified from the
    # consumed cursor; the orphans are protocol-deleted (ignored)
    r = FeedbackReader(d)
    assert r.pending(cur) == 0
    w.append_batch(np.ones((4, 16), np.float32),
                   np.zeros((4, 1), np.float32))
    w.flush()
    recs, _ = r.read_since(cur)
    assert len(recs) == 4
    # the next sweep deletes the orphans without moving the boundary
    out = Sweeper(d, RetentionOptions(0, 0)).sweep(cur)
    assert out["compacted_below"] == boundary
    assert out["deleted_shards"] == n_files - len(list_shards(d))
    assert all(idx >= boundary for idx, _ in list_shards(d))
    w.close()


def test_writer_never_resumes_below_retention_boundary(tmp_path):
    """Every shard compacted away + writer restart: reusing index 0
    would put new records BEHIND the boundary where readers must
    ignore them."""
    import json as _json

    from cxxnet_tpu.loop.feedback_log import RETENTION_FILE

    d = str(tmp_path / "log")
    os.makedirs(d)
    with open(os.path.join(d, RETENTION_FILE), "w") as f:
        _json.dump({"compacted_below": 5}, f)
    w = FeedbackWriter(d)
    w.append_batch(np.ones((2, 16), np.float32),
                   np.zeros((2, 1), np.float32))
    w.flush()
    (idx, _), = list_shards(d)
    assert idx == 5
    recs, _ = FeedbackReader(d).read_since({"shard": 5, "off": 0})
    assert len(recs) == 2
    w.close()


def test_loop_cycle_sweeps_retention_end_to_end(tmp_path):
    """The closed loop with retention armed: a published cycle's sweep
    reclaims the consumed shards and the disk gauge drops."""
    from cxxnet_tpu.loop.retention import RetentionOptions, Sweeper

    cfg, mdir, _ = make_trained_checkpoint(tmp_path)
    eng = serve.Engine(cfg=cfg, model_dir=mdir, max_batch_size=32)
    try:
        fdir = str(tmp_path / "feedback")
        w = FeedbackWriter(fdir, page_bytes=2048, rotate_bytes=4096)
        X, Y = synth_rows(synth_iter())
        w.append_batch(X, Y)
        w.flush()
        shards_before = len(list_shards(fdir))
        assert shards_before > 1
        loop = ContinuousLoop(
            eng, cfg, feedback_dir=fdir, base_iter=synth_iter(),
            eval_iter=synth_iter(), rounds_per_cycle=2, min_records=64,
            feedback_writer=w,
            retention=Sweeper(fdir, RetentionOptions(0, 0),
                              tenant="e2e"),
            silent=True,
        )
        bytes_before = sum(os.path.getsize(p)
                           for _, p in list_shards(fdir))
        assert loop.run_cycle() == "published"
        assert len(list_shards(fdir)) < shards_before
        after = _counter_value("feedback_disk_bytes", tenant="e2e")
        assert 0 < after < bytes_before
        # cursor and reader agree after compaction: next cycle is idle
        assert loop.run_cycle() == "idle"
        w.close()
    finally:
        eng.close()


# ----------------------------------------------------------------------
# per-model routing (serve/router.py ModelRouter + HTTP front-end)
def test_model_router_resolve_default_and_unknown():
    from cxxnet_tpu.serve.router import ModelRouter, UnknownModelError

    ea, eb = object(), object()
    r = ModelRouter()
    r.add("a", ea).add("b", eb, feedback="fb")
    assert r.resolve(None) == ("a", ea, None)  # first added = default
    assert r.resolve("") == ("a", ea, None)
    assert r.resolve("b") == ("b", eb, "fb")
    assert r.models() == ["a", "b"]
    with pytest.raises(UnknownModelError) as e:
        r.resolve("nope")
    assert e.value.reason == "unknown_model"
    assert e.value.known == ["a", "b"]
    assert "nope" in str(e.value)
    with pytest.raises(ValueError):
        r.add("a", ea)  # duplicate
    with pytest.raises(ValueError):
        r.add("", ea)
    r2 = ModelRouter()
    r2.add("x", ea).add("y", eb, default=True)
    assert r2.resolve(None)[0] == "y"  # explicit default wins


def test_http_per_model_routing(tmp_path):
    """The request's model field selects the tenant's engine + feedback
    log; unknown model is a 404 with the machine-readable reason."""
    from cxxnet_tpu.serve.router import ModelRouter

    cfg, mdir_a, _ = make_trained_checkpoint(tmp_path / "a")
    _, mdir_b, _ = make_trained_checkpoint(tmp_path / "b", rounds=2,
                                           seed=1)
    ea = serve.Engine(cfg=cfg, model_dir=mdir_a, max_batch_size=32,
                      batch_timeout_ms=1)
    eb = serve.Engine(cfg=cfg, model_dir=mdir_b, max_batch_size=32,
                      batch_timeout_ms=1)
    wa = FeedbackWriter(str(tmp_path / "fa"))
    wb = FeedbackWriter(str(tmp_path / "fb"))
    router = ModelRouter()
    router.add("alpha", ea, feedback=wa)
    router.add("beta", eb, feedback=wb)
    httpd = serve.make_server(ea, port=0, feedback=wa, router=router)
    port = httpd.server_port
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    x = np.random.RandomState(0).randn(4, 16).astype(np.float32)
    try:
        # /healthz names every model with its identity + default flag
        h = _get(port, "/healthz")
        assert set(h["models"]) == {"alpha", "beta"}
        assert h["models"]["alpha"]["default"] is True
        assert h["models"]["alpha"]["model_crc32"] == ea.model_crc32
        assert h["models"]["beta"]["model_crc32"] == eb.model_crc32
        assert _get(port, "/statsz")["models"] == ["alpha", "beta"]
        # /predict dispatches by model; both engines answer
        assert len(_post(port, "/predict",
                         {"data": x.tolist(), "model": "beta"})["pred"]) == 4
        assert len(_post(port, "/predict",
                         {"data": x.tolist()})["pred"]) == 4
        # /feedback routes to the NAMED tenant's log
        out = _post(port, "/feedback",
                    {"data": x.tolist(), "label": [0, 1, 2, 3],
                     "model": "beta"})
        assert out["appended"] == 4
        wb.flush()
        wa.flush()
        assert len(FeedbackReader(str(tmp_path / "fb"))
                   .read_since(None)[0]) == 4
        assert FeedbackReader(str(tmp_path / "fa")).read_since(
            None)[0] == []  # alpha's log untouched
        # model-less /feedback takes the default route (alpha)
        _post(port, "/feedback", {"data": x.tolist(),
                                  "label": [0, 1, 2, 3]})
        wa.flush()
        assert len(FeedbackReader(str(tmp_path / "fa"))
                   .read_since(None)[0]) == 4
        # unknown model: 404 with the machine-readable reason token
        for path in ("/predict", "/feedback", "/extract"):
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(port, path, {"data": x.tolist(), "label": [0] * 4,
                                   "node": "fc1", "model": "ghost"})
            assert e.value.code == 404
            body = json.loads(e.value.read())
            assert body["reason"] == "unknown_model"
            assert body["models"] == ["alpha", "beta"]
    finally:
        httpd.shutdown()
        httpd.server_close()
        wa.close()
        wb.close()
        ea.close()
        eb.close()


# ----------------------------------------------------------------------
# the tenant manager: N loops, one pool, SLO-constrained arbiter
def _tenant_fixture(tmp_path, names=("alpha", "beta")):
    from cxxnet_tpu.loop.tenant import TenantManager

    shared = cfgmod.parse_pairs(MLP_CFG)
    secs = []
    for i, name in enumerate(names):
        _, mdir, _ = make_trained_checkpoint(tmp_path / name, seed=i)
        secs.append(cfgmod.TenantSection(name, [
            ("model_dir", mdir),
            ("feedback_dir", str(tmp_path / name / "feedback")),
            ("feedback_page_bytes", "2048"),
            ("feedback_rotate_bytes", "4096"),
            ("feedback_retain_shards", "0"),
        ]))
    mgr = TenantManager(
        shared, secs,
        engine_factory=lambda cfg, mdir: serve.Engine(
            cfg=cfg, model_dir=mdir, max_batch_size=32),
        make_iters=lambda cfg: (synth_iter(), synth_iter(), "eval"),
        loop_dir=str(tmp_path / "loop"),
    )
    return mgr


def test_tenant_manager_two_tenants_share_one_pool(tmp_path):
    """Two tenants tick round-robin on one device pool: the poisoned
    tenant rejects (cohort-attributable in its own event stream), the
    healthy one publishes, retention compacts behind both, and an SLO
    alert sheds ALL tune cycles."""
    mgr = _tenant_fixture(tmp_path)
    try:
        alpha, beta = mgr.tenants
        assert mgr.tenant("beta") is beta
        with pytest.raises(KeyError):
            mgr.tenant("ghost")
        # no feedback yet: both idle
        assert mgr.tick_once() == {"alpha": "idle", "beta": "idle"}
        X, Y = synth_rows(synth_iter())
        alpha.feedback.append_batch(X, Y)  # correct labels
        beta.feedback.append_batch(X[:200], (Y[:200] + 1.0) % 4)
        shards_a = len(list_shards(alpha.feedback_dir))
        out = mgr.tick_once()
        assert out == {"alpha": "published", "beta": "rejected"}
        # a publish feeds the arbiter's work objective
        assert mgr.arbiter.work() >= 1.0
        assert alpha.engine.round == 2 and beta.engine.round == 1
        # retention ran behind the resolved cursors
        assert len(list_shards(alpha.feedback_dir)) < shards_a
        # per-tenant outcome counters
        assert _counter_value("tenant_cycles_total", tenant="alpha",
                              outcome="published") >= 1
        assert _counter_value("tenant_cycles_total", tenant="beta",
                              outcome="rejected") >= 1
        # SLO overlay: a firing alert sheds EVERY tenant's tune cycle
        mgr.arbiter.slo_firing = lambda: ["serve_p99_high"]
        sheds0 = _counter_value("loop_shed_total")
        assert mgr.tick_once() == {"alpha": "shed", "beta": "shed"}
        assert _counter_value("loop_shed_total") == sheds0 + 1
        assert mgr.arbiter.shedding
        from cxxnet_tpu.obs import recent

        assert any(e["kind"] == "tenant.shed" for e in recent(10))
        # alert clears: training resumes
        mgr.arbiter.slo_firing = lambda: []
        out = mgr.tick_once()
        assert set(out.values()) <= {"idle", "published", "rejected"}
        assert not mgr.arbiter.shedding
        # the HTTP router covers every tenant; healthz names them
        r = mgr.router()
        assert r.models() == ["alpha", "beta"]
        assert r.resolve(None)[0] == "alpha"  # first tenant = default
        hz = mgr.healthz_tenants()
        assert hz["alpha"]["round"] == 2
    finally:
        mgr.close()


def test_tenant_manager_isolation_and_knobs(tmp_path):
    """One tenant's broken cycle must not starve its neighbor, and the
    arbiter's per-tenant round knobs bind to the live loops."""
    mgr = _tenant_fixture(tmp_path)
    try:
        alpha, beta = mgr.tenants
        X, Y = synth_rows(synth_iter())
        alpha.feedback.append_batch(X, Y)
        beta.loop.run_cycle = None  # not callable -> TypeError inside
        out = mgr.tick_once()
        assert out["alpha"] == "published"
        assert out["beta"] == "error"
        # knobs: one per tenant, bound to rounds_per_cycle
        knobs = mgr.arbiter.controller.knobs
        assert sorted(k.name for k in knobs) == [
            "tenant_rounds:alpha", "tenant_rounds:beta"]
        k = next(k for k in knobs if k.name.endswith("alpha"))
        k.apply(5)
        assert alpha.loop.rounds_per_cycle == 5
        assert k.read() == 5
        k.apply(0)  # floor is 1
        assert alpha.loop.rounds_per_cycle == 1
    finally:
        mgr.close()


def test_tenant_manager_requires_model_dir_and_sections(tmp_path):
    from cxxnet_tpu.loop.tenant import TenantManager

    with pytest.raises(ValueError, match="at least one"):
        TenantManager(cfgmod.parse_pairs(MLP_CFG), [],
                      engine_factory=None, make_iters=None)
    with pytest.raises(ValueError, match="model_dir"):
        TenantManager(
            cfgmod.parse_pairs(MLP_CFG),
            [cfgmod.TenantSection("a", [])],
            engine_factory=lambda cfg, mdir: None,
            make_iters=lambda cfg: (None, synth_iter(), "eval"))
