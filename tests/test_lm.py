"""Language-model pipeline: text iterator, embedding, per-position
softmax, end-to-end training + generation (all new TPU-first scope —
the reference has no sequence models, SURVEY §5)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cxxnet_tpu import config as cfgmod
from cxxnet_tpu.io.data import DataBatch, create_iterator
from cxxnet_tpu.io.text import TextIterator
from cxxnet_tpu.layers import create_layer
from cxxnet_tpu.models import transformer_lm_conf
from cxxnet_tpu.nnet.trainer import NetTrainer


@pytest.fixture()
def corpus(tmp_path):
    p = tmp_path / "c.txt"
    p.write_bytes(("the quick brown fox jumps over the lazy dog. " * 300)
                  .encode())
    return str(p)


def _text_iter(corpus, **kw):
    it = TextIterator()
    it.set_param("filename", corpus)
    it.set_param("silent", "1")
    for k, v in kw.items():
        it.set_param(k, str(v))
    it.init()
    return it


def test_text_iterator_next_byte_shift(corpus):
    it = _text_iter(corpus, seq_len=8, batch_size=4)
    it.before_first()
    assert it.next()
    b = it.value()
    assert b.data.shape == (4, 8) and b.label.shape == (4, 8)
    # label is the input shifted by one byte
    np.testing.assert_array_equal(b.data[:, 1:], b.label[:, :-1])
    raw = open(corpus, "rb").read()
    np.testing.assert_array_equal(b.data[0], np.frombuffer(raw[:8], np.uint8))
    assert b.label[0, -1] == raw[8]


def test_text_iterator_dist_shard(corpus):
    it = _text_iter(corpus, seq_len=16, batch_size=2)
    full = sum(1 for _ in iter(lambda: it.next(), False))
    counts = []
    for rank in range(2):
        ws = _text_iter(corpus, seq_len=16, batch_size=2,
                        dist_num_worker=2, dist_worker_rank=rank)
        assert ws.supports_dist_shard()
        counts.append(sum(1 for _ in iter(lambda: ws.next(), False)))
    assert counts[0] == counts[1]
    assert counts[0] <= (full + 1) // 2


def test_embedding_layer_lookup_and_positions():
    lay = create_layer("embedding")
    lay.set_param("nvocab", "7")
    lay.set_param("nhidden", "4")
    lay.set_param("init_sigma", "1.0")
    lay.infer_shape([(2, 3)])
    params = lay.init_params(jax.random.PRNGKey(0), [(2, 3)])
    ids = jnp.asarray([[0, 3, 6], [1, 1, 2]], jnp.float32)
    (out,) = lay.apply(params, [ids])
    assert out.shape == (2, 3, 4)
    np.testing.assert_allclose(np.asarray(out[0, 1]),
                               np.asarray(params["wmat"][3]))
    np.testing.assert_allclose(np.asarray(out[1, 0]),
                               np.asarray(out[1, 1]))

    # learned positions break the tie between equal tokens
    lay2 = create_layer("embedding")
    lay2.set_param("nvocab", "7")
    lay2.set_param("nhidden", "4")
    lay2.set_param("pos", "learned")
    lay2.infer_shape([(2, 3)])
    p2 = lay2.init_params(jax.random.PRNGKey(1), [(2, 3)])
    assert "pos" in p2
    (out2,) = lay2.apply(p2, [ids])
    assert not np.allclose(np.asarray(out2[1, 0]), np.asarray(out2[1, 1]))

    # sinusoidal: fixed, no extra params
    lay3 = create_layer("embedding")
    lay3.set_param("nvocab", "7")
    lay3.set_param("nhidden", "4")
    lay3.set_param("pos", "sin")
    lay3.infer_shape([(2, 3)])
    p3 = lay3.init_params(jax.random.PRNGKey(2), [(2, 3)])
    assert set(p3) == {"wmat"}
    with pytest.raises(ValueError, match="pos"):
        create_layer("embedding").set_param("pos", "rotary")


def test_embedding_gradient_hits_used_rows_only():
    lay = create_layer("embedding")
    lay.set_param("nvocab", "5")
    lay.set_param("nhidden", "3")
    lay.set_param("init_sigma", "0.5")
    lay.infer_shape([(1, 2)])
    params = lay.init_params(jax.random.PRNGKey(0), [(1, 2)])
    ids = jnp.asarray([[1, 3]], jnp.float32)

    g = jax.grad(
        lambda p: lay.apply(p, [ids])[0].sum()
    )(params)["wmat"]
    g = np.asarray(g)
    assert np.all(g[[1, 3]] == 1.0)
    assert np.all(g[[0, 2, 4]] == 0.0)


def test_softmax_loss_per_position_matches_manual():
    from cxxnet_tpu.layers.loss import SoftmaxLayer

    lay = SoftmaxLayer()
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 3, 5).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 5, (2, 3)).astype(np.float32))
    got = float(lay.loss(x, y))
    logp = np.asarray(jax.nn.log_softmax(x, axis=-1))
    want = -sum(
        logp[n, t, int(np.asarray(y)[n, t])]
        for n in range(2) for t in range(3)
    )
    assert abs(got - want) < 1e-4
    # 2-D classifier case unchanged
    x2 = jnp.asarray(rng.randn(4, 5).astype(np.float32))
    y2 = jnp.asarray(rng.randint(0, 5, (4, 1)).astype(np.float32))
    got2 = float(lay.loss(x2, y2))
    logp2 = np.asarray(jax.nn.log_softmax(x2, axis=-1))
    want2 = -sum(logp2[i, int(np.asarray(y2)[i, 0])] for i in range(4))
    assert abs(got2 - want2) < 1e-4


def test_metric_flattens_sequence_predictions():
    from cxxnet_tpu.utils.metric import MetricSet

    ms = MetricSet()
    ms.add_metric("error")
    pred = np.zeros((2, 3, 4), np.float32)
    pred[0, :, 1] = 1.0  # predicts class 1 at all positions of row 0
    pred[1, :, 2] = 1.0
    label = np.asarray([[1, 1, 0], [2, 2, 2]], np.float32)
    ms.add_eval(pred, label, {"label": (0, 3)})
    assert abs(ms.metrics[0].get() - 1.0 / 6.0) < 1e-6


def _lm_trainer(corpus, **kw):
    conf = transformer_lm_conf(
        seq_len=32, dim=64, nhead=2, nlayer=2, text_file=corpus,
        batch_size=16, dev="cpu", compute_dtype="float32", **kw,
    )
    pairs = cfgmod.parse_pairs(conf)
    it = create_iterator(
        cfgmod.split_sections(pairs).find("data")[0].entries
    )
    it.set_param("batch_size", "16")
    it.set_param("silent", "1")
    it.init()
    tr = NetTrainer()
    tr.set_params(pairs)
    tr.init_model()
    return tr, it


@pytest.mark.slow
def test_lm_trains_and_generates(corpus):
    tr, it = _lm_trainer(corpus)
    for _ in range(12):
        it.before_first()
        while it.next():
            tr.update(it.value())
    it.before_first()
    it.next()
    b = it.value()
    out = np.asarray(tr.predict(b))
    assert out.shape == b.label.shape
    acc = (out == b.label).mean()
    assert acc > 0.8, f"LM failed to overfit: next-byte acc {acc:.2f}"

    # greedy generation continues the periodic corpus
    t = tr.graph.input_shape[-1]
    ctx = list(b"the quick brown fox ")
    for _ in range(30):
        window = ctx[-t:]
        data = np.zeros((1, t), np.float32)
        data[0, : len(window)] = window
        probs = tr.extract_feature(
            DataBatch(data=data, label=None), "top[-1]"
        )[0, len(window) - 1]
        ctx.append(int(np.argmax(probs)))
    text = bytes(ctx[20:]).decode("utf-8", "replace")
    assert "jumps over" in text, f"unexpected continuation: {text!r}"


def test_text_iterator_round_batch_pads_final(corpus):
    it = _text_iter(corpus, seq_len=16, batch_size=64)
    n_windows = len(it._starts)
    it.before_first()
    total = 0
    last = None
    while it.next():
        last = it.value()
        total += last.batch_size - last.num_batch_padd
    assert total == n_windows  # every window served exactly once
    if n_windows % 64:
        assert last.num_batch_padd == 64 - n_windows % 64
        assert last.data.shape == (64, 16)
        # pad rows reuse the LEADING windows; inst_index must mirror the
        # actual rows served (advisor r2: arange past len(starts) used to
        # misattribute prediction bookkeeping for wrapped rows)
        padd = last.num_batch_padd
        np.testing.assert_array_equal(
            last.inst_index[-padd:], np.arange(padd)
        )
        assert last.inst_index.max() < n_windows
    # round_batch = 0 drops the partial batch (mnist-style)
    it2 = _text_iter(corpus, seq_len=16, batch_size=64, round_batch=0)
    it2.before_first()
    total2 = 0
    while it2.next():
        total2 += it2.value().batch_size
    assert total2 == (n_windows // 64) * 64


def test_metric_rejects_mismatched_sequence_field():
    from cxxnet_tpu.utils.metric import MetricSet

    ms = MetricSet()
    ms.add_metric("error", field="aux")
    pred = np.zeros((2, 3, 4), np.float32)
    label = np.zeros((2, 4), np.float32)
    with pytest.raises(ValueError, match="width 3"):
        ms.add_eval(pred, label, {"aux": (3, 4)})


def test_gen_prompt_file_read_lazily(tmp_path):
    """A conf naming a missing gen_prompt_file must not break parsing —
    the file is only read by task=generate."""
    from cxxnet_tpu.cli import LearnTask

    task = LearnTask()
    task.set_param("gen_prompt_file", str(tmp_path / "nope.txt"))
    assert task.gen_prompt_file.endswith("nope.txt")  # stored, not read


def test_integer_input_keyed_to_graph_not_position():
    """bf16 nets keep raw ids in f32 whenever ANY consumer of node 0 is
    an embedding, regardless of declaration order."""
    from cxxnet_tpu.nnet.graph import NetGraph
    from cxxnet_tpu.nnet.net import FunctionalNet

    cfg = [
        ("batch_size", "2"),
        ("input_shape", "1,1,4"),
        ("compute_dtype", "bfloat16"),
        ("netconfig", "start"),
        # a non-embedding layer declared FIRST, also reading node 0
        ("layer[0->aux]", "fullc:aux"),
        ("nhidden", "3"),
        ("layer[0->emb]", "embedding:embed"),
        ("nvocab", "300"),
        ("nhidden", "3"),
        ("layer[emb->pool]", "seq_pool"),
        ("layer[aux,pool->sum]", "eltwise_sum"),
        ("layer[sum->fc]", "fullc:fc"),
        ("nhidden", "2"),
        ("layer[fc->fc]", "softmax"),
        ("netconfig", "end"),
    ]
    g = NetGraph()
    g.configure(cfg)
    net = FunctionalNet(g)
    assert net._node0_wants_ints()


@pytest.mark.slow
@pytest.mark.parametrize("sp_mode,attn_impl,rtol", [
    (2, "auto", 2e-4),      # Ulysses all-to-all
    (1, "pallas", 5e-4),    # flash ring (per-hop Pallas kernel)
])
def test_lm_seq_parallel_fsdp_matches_single(corpus, sp_mode, attn_impl,
                                             rtol):
    """The LM composed with sequence parallelism over the model axis AND
    ZeRO-3 param sharding trains the same weights as a single device —
    the full new-scope stack in one net, for both SP schedules."""
    results = {}
    for mode in ("single", "sharded"):
        conf = transformer_lm_conf(
            seq_len=32, dim=32, nhead=2, nlayer=1, text_file=corpus,
            batch_size=16, dev="cpu" if mode == "single" else "cpu:0-7",
            compute_dtype="float32",
            seq_parallel=0 if mode == "single" else sp_mode,
            attn_impl="xla" if mode == "single" else attn_impl,
        )
        pairs = cfgmod.parse_pairs(conf)
        it = create_iterator(
            cfgmod.split_sections(pairs).find("data")[0].entries
        )
        it.set_param("batch_size", "16")
        it.set_param("silent", "1")
        it.init()
        tr = NetTrainer()
        tr.set_params(pairs)
        if mode == "sharded":
            tr.set_param("model_parallel", "2")
            tr.set_param("zero", "3")
        tr.init_model()
        it.before_first()
        steps = 0
        while it.next() and steps < 5:
            tr.update(it.value())
            steps += 1
        results[mode] = {
            k: {t: np.asarray(v) for t, v in tags.items()}
            for k, tags in tr.params.items()
        }
        if mode == "sharded":
            assert tr.mesh_plan.n_model == 2 and tr.mesh_plan.n_data == 4
    for key in results["single"]:
        for tag in results["single"][key]:
            np.testing.assert_allclose(
                results["sharded"][key][tag], results["single"][key][tag],
                rtol=rtol, atol=rtol / 10,
                err_msg=f"{key}/{tag} diverged under SP+FSDP",
            )


def test_lm_gradient_accumulation_matches_big_batch(corpus):
    """update_period=2 with per-position sequence labels equals one
    double-size batch (the accumulation path must handle (N,T) labels)."""
    conf = transformer_lm_conf(
        seq_len=16, dim=32, nhead=2, nlayer=1, text_file=corpus,
        batch_size=8, dev="cpu", compute_dtype="float32",
    )
    pairs = cfgmod.parse_pairs(conf)
    rng = np.random.RandomState(0)
    data = rng.randint(0, 255, (16, 16)).astype(np.float32)
    labels = rng.randint(0, 255, (16, 16)).astype(np.float32)

    # accumulated: two micro-batches of 8 per update
    t_acc = NetTrainer()
    t_acc.set_params(pairs)
    t_acc.set_param("update_period", "2")
    t_acc.init_model()
    t_acc.update_all(data[:8], labels[:8])
    t_acc.update_all(data[8:], labels[8:])

    # one batch of 16 with halved per-token scale (grad_scale already
    # divides by batch*update_period — the semantics under test)
    conf2 = transformer_lm_conf(
        seq_len=16, dim=32, nhead=2, nlayer=1, text_file=corpus,
        batch_size=16, dev="cpu", compute_dtype="float32",
    )
    t_big = NetTrainer()
    t_big.set_params(cfgmod.parse_pairs(conf2))
    t_big.init_model()
    t_big.update_all(data, labels)

    for key in t_big.params:
        for tag in t_big.params[key]:
            np.testing.assert_allclose(
                np.asarray(t_acc.params[key][tag]),
                np.asarray(t_big.params[key][tag]),
                rtol=2e-4, atol=2e-5,
                err_msg=f"{key}/{tag}: accumulation != big batch",
            )


def test_attention_decode_matches_full_causal():
    """Token-by-token KV-cache attention equals full causal attention."""
    from cxxnet_tpu.layers import create_layer

    rng = np.random.RandomState(3)
    T, D = 8, 16
    x = jnp.asarray(rng.randn(2, T, D).astype(np.float32))

    full = create_layer("attention")
    for k, v in (("nhead", "2"), ("causal", "1"), ("init_sigma", "0.1")):
        full.set_param(k, v)
    full.infer_shape([(2, T, D)])
    params = full.init_params(jax.random.PRNGKey(0), [(2, T, D)])
    (want,) = full.apply(params, [x])

    dec = create_layer("attention")
    for k, v in (("nhead", "2"), ("causal", "1"), ("init_sigma", "0.1"),
                 ("decode", "1"), ("decode_window", str(T))):
        dec.set_param(k, v)
    dec.infer_shape([(2, 1, D)])
    aux = dec.init_aux([(2, 1, D)])
    outs = []
    for t in range(T):
        (o,), aux = dec.apply_stateful(
            params, aux, [x[:, t:t + 1]], step=jnp.asarray(t, jnp.int32)
        )
        outs.append(o)
    got = jnp.concatenate(outs, axis=1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_attention_decode_guards():
    from cxxnet_tpu.layers import create_layer

    lay = create_layer("attention")
    lay.set_param("nhead", "2")
    lay.set_param("decode", "1")
    with pytest.raises(ValueError, match="causal"):
        lay.init_aux([(1, 1, 8)])  # bidirectional can't decode
    lay.set_param("causal", "1")
    with pytest.raises(ValueError, match="decode_window"):
        lay.init_aux([(1, 1, 8)])
    lay.set_param("decode_window", "16")
    lay.set_param("seq_parallel", "ring")
    with pytest.raises(ValueError, match="seq_parallel"):
        lay.init_aux([(1, 1, 8)])


@pytest.mark.slow
def test_lm_cached_decode_matches_full_forward(corpus):
    """The decode twin (input (1,1), KV caches in aux, absolute
    positions via step) reproduces the trained net's per-position
    probabilities exactly — the cli gen_cache=1 recipe."""
    from cxxnet_tpu.io.data import DataBatch

    tr, it = _lm_trainer(corpus)
    for _ in range(3):
        it.before_first()
        while it.next():
            tr.update(it.value())

    t_train = tr.graph.input_shape[-1]
    dec_cfg = []
    for n, v in tr.cfg:
        if n == "input_shape":
            v = "1,1,1"
        elif n == "batch_size":
            v = "1"
        dec_cfg.append((n, v))
    dec_cfg += [("decode", "1"), ("decode_window", str(t_train)),
                ("batch_size", "1")]
    from cxxnet_tpu.nnet.trainer import NetTrainer as NT

    dec = NT()
    dec.set_params(dec_cfg)
    dec.init_model()
    for key in dec.params:
        dec.params[key] = tr.params[key]

    ids = list(b"the quick brown fox jumps over t")[:t_train]
    full = tr.extract_feature(
        DataBatch(data=np.asarray([ids], np.float32), label=None), "top[-1]"
    )[0]  # (T, V) probs
    net = dec.net
    out_idx = net.out_node_index()
    aux = net.init_aux(1)
    for pos, tok in enumerate(ids):
        nodes, _, aux = net.forward(
            dec.params, np.asarray([[tok]], np.float32), train=False,
            aux=aux, return_aux=True, step=jnp.asarray(pos, jnp.int32),
        )
        got = np.asarray(nodes[out_idx].astype(jnp.float32))[0, 0]
        np.testing.assert_allclose(
            got, full[pos], rtol=2e-4, atol=2e-5,
            err_msg=f"decode twin diverged at position {pos}",
        )


@pytest.mark.slow
def test_net_generate_wrapper_api(corpus):
    """Python-API generation (Net.generate): cached and windowed paths
    agree greedily, and over-window requests fall back transparently."""
    from cxxnet_tpu.wrapper import Net

    conf = transformer_lm_conf(
        seq_len=32, dim=64, nhead=2, nlayer=2, text_file=corpus,
        batch_size=16, dev="cpu", compute_dtype="float32",
    )
    net = Net(dev="cpu", cfg=conf)
    net.init_model()
    it = create_iterator(
        cfgmod.split_sections(cfgmod.parse_pairs(conf)).find("data")[0]
        .entries
    )
    it.set_param("batch_size", "16")
    it.set_param("silent", "1")
    it.init()
    for _ in range(10):
        it.before_first()
        while it.next():
            b = it.value()
            net.update(b.data, b.label)
    cached = net.generate("the quick ", gen_len=20)
    windowed = net.generate("the quick ", gen_len=20, cache=False)
    assert cached == windowed
    assert "brown" in cached
    # over-window request: falls back to windows, honors gen_len
    long = net.generate("the quick ", gen_len=60)
    assert len(long.encode("utf-8", "replace")) >= 60 - 3  # multibyte slack


def test_sample_token_topk_topp():
    from cxxnet_tpu.nnet.generate import sample_token

    rng = np.random.RandomState(0)
    p = np.asarray([0.5, 0.3, 0.15, 0.05])
    # greedy ignores truncation
    assert sample_token(p, rng, 0.0, topk=1) == 0
    # topk=2: only tokens 0/1 ever drawn
    draws = {sample_token(p, rng, 1.0, topk=2) for _ in range(200)}
    assert draws <= {0, 1}
    # topp=0.6: nucleus is {0, 1} (0.5 + 0.3 >= 0.6)
    draws = {sample_token(p, rng, 1.0, topp=0.6) for _ in range(200)}
    assert draws <= {0, 1}
    # no truncation: all tokens reachable
    draws = {sample_token(p, rng, 1.0) for _ in range(500)}
    assert draws == {0, 1, 2, 3}


def test_perplexity_metric():
    import math

    from cxxnet_tpu.utils.metric import MetricSet

    ms = MetricSet()
    ms.add_metric("perplexity")
    # uniform over 4 classes -> perplexity 4, per token
    pred = np.full((2, 3, 4), 0.25, np.float32)
    label = np.zeros((2, 3), np.float32)
    ms.add_eval(pred, label, {"label": (0, 3)})
    assert abs(ms.metrics[0].get() - 4.0) < 1e-6
    assert abs(math.log(ms.metrics[0].get()) -
               (-math.log(0.25))) < 1e-6


def test_lm_remat_with_flash_matches_no_remat(corpus):
    """remat=1 (jax.checkpoint per layer) composed with the Pallas flash
    kernel's custom VJP: training must be numerically identical to
    remat=0 (activation recompute changes memory, not math)."""
    results = {}
    for remat in ("0", "1"):
        conf = transformer_lm_conf(
            seq_len=16, dim=32, nhead=2, nlayer=1, text_file=corpus,
            batch_size=8, dev="cpu", compute_dtype="float32",
            attn_impl="pallas",
        )
        pairs = cfgmod.parse_pairs(conf) + [("remat", remat)]
        tr = NetTrainer()
        tr.set_params(pairs)
        tr.init_model()
        rng = np.random.RandomState(0)
        data = rng.randint(0, 255, (8, 16)).astype(np.float32)
        labels = rng.randint(0, 255, (8, 16)).astype(np.float32)
        for _ in range(3):
            tr.update_all(data, labels)
        results[remat] = {
            k: {t: np.asarray(v) for t, v in tags.items()}
            for k, tags in tr.params.items()
        }
    for key in results["0"]:
        for tag in results["0"][key]:
            np.testing.assert_allclose(
                results["1"][key][tag], results["0"][key][tag],
                rtol=1e-4, atol=1e-6,
                err_msg=f"{key}/{tag}: remat changed the math",
            )


def test_generate_seed_determinism(corpus):
    """Same seed -> same sample; different seed -> (almost surely)
    different sample at high temperature."""
    tr, it = _lm_trainer(corpus)
    it.before_first()
    it.next()
    tr.update(it.value())
    from cxxnet_tpu.nnet.generate import generate

    a = generate(tr, "the ", gen_len=12, temp=1.5, seed=1)
    b = generate(tr, "the ", gen_len=12, temp=1.5, seed=1)
    c = generate(tr, "the ", gen_len=12, temp=1.5, seed=2)
    assert a == b
    assert a != c


def test_task_summary_on_lm_conf(tmp_path, capsys, corpus):
    """task=summary handles sequence graphs (embedding, attention)."""
    from cxxnet_tpu import cli as climod

    conf = tmp_path / "lm.conf"
    conf.write_text(transformer_lm_conf(
        seq_len=16, dim=32, nhead=2, nlayer=1, text_file=corpus,
        batch_size=4, dev="cpu", compute_dtype="float32",
    ))
    rc = climod.main([str(conf), "task=summary", "silent=1"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "embedding" in out and "attention" in out
    assert "total parameters:" in out

