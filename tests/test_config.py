"""Config grammar tests: tokenizer, pair stream, section splitting.

Fixtures mirror the reference example configs (MNIST.conf, ImageNet.conf,
bowl.conf) to prove the grammar handles every construct they use.
"""

import pytest

hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings  # noqa: E402
from hypothesis import strategies as st  # noqa: E402

from cxxnet_tpu import config as C


def test_basic_pairs():
    assert C.parse_pairs("a = 1\nb=2\n c =3") == [("a", "1"), ("b", "2"), ("c", "3")]


def test_comments_and_blanks():
    text = """
# leading comment
a = 1  # trailing comment
# another

b = 2
"""
    assert C.parse_pairs(text) == [("a", "1"), ("b", "2")]


def test_quoted_strings():
    text = 'path_img = "./data/train images.idx"\nx = "a=b#c"'
    assert C.parse_pairs(text) == [
        ("path_img", "./data/train images.idx"),
        ("x", "a=b#c"),
    ]


def test_multiline_string():
    text = "doc = 'line1\nline2'\nb = 2"
    assert C.parse_pairs(text) == [("doc", "line1\nline2"), ("b", "2")]


def test_escape_in_string():
    assert C.parse_pairs(r'x = "a\"b"') == [("x", 'a"b')]


def test_equals_own_token_no_spaces():
    assert C.parse_pairs("layer[0->1]=conv:cv1") == [("layer[0->1]", "conv:cv1")]


def test_name_value_must_share_line():
    with pytest.raises(C.ConfigError):
        C.parse_pairs("a\n= 1")
    with pytest.raises(C.ConfigError):
        C.parse_pairs("a =\n1")


def test_dangling_token_raises():
    with pytest.raises(C.ConfigError):
        C.parse_pairs("a = 1\nstray")


def test_mnist_conf_like():
    text = """
data = train
iter = mnist
    path_img = "./data/train-images-idx3-ubyte"
    shuffle = 1
iter = end
eval = test
iter = mnist
    path_img = "./data/t10k-images-idx3-ubyte"
iter = end

netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 100
layer[+0] = softmax
netconfig=end

input_shape = 1,1,784
batch_size = 100
eta = 0.1
metric[label] = error
"""
    cfg = C.parse_pairs(text)
    split = C.split_sections(cfg)
    assert [s.kind for s in split.sections] == ["data", "eval"]
    assert split.sections[0].tag == "train"
    assert split.sections[0].entries[0] == ("iter", "mnist")
    assert ("shuffle", "1") in split.sections[0].entries
    assert split.sections[1].tag == "test"
    names = [n for n, _ in split.global_entries]
    assert "netconfig" in names and "batch_size" in names
    assert C.cfg_get(split.global_entries, "input_shape") == "1,1,784"
    assert ("metric[label]", "error") in split.global_entries


def test_pred_section():
    cfg = C.parse_pairs("pred = out.txt\niter = csv\niter = end\n")
    split = C.split_sections(cfg)
    assert split.sections[0].kind == "pred"
    assert split.sections[0].tag == "out.txt"


def test_unclosed_section_raises():
    with pytest.raises(C.ConfigError):
        C.split_sections(C.parse_pairs("data = train\niter = mnist"))


def test_threadbuffer_chain_kept_in_order():
    cfg = C.parse_pairs("data = train\niter = imgbin\nrand_crop=1\niter = threadbuffer\niter = end\n")
    split = C.split_sections(cfg)
    ent = split.sections[0].entries
    assert ent == [("iter", "imgbin"), ("rand_crop", "1"), ("iter", "threadbuffer")]


def test_cli_overrides():
    assert C.parse_cli_overrides(["num_round=3", "notakv", "dev=tpu:0-3"]) == [
        ("num_round", "3"),
        ("dev", "tpu:0-3"),
    ]


def test_cfg_get_last_wins():
    cfg = [("dev", "cpu"), ("dev", "gpu:1")]
    assert C.cfg_get(cfg, "dev") == "gpu:1"
    assert C.cfg_get(cfg, "missing", "d") == "d"


def test_reopened_section_raises():
    with pytest.raises(C.ConfigError):
        C.split_sections(
            C.parse_pairs("data = train\niter = mnist\neval = test\niter = end\n")
        )


def test_reference_example_confs_parse():
    """The shipped reference configs must tokenize and split cleanly."""
    import os

    if not os.path.isdir("/root/reference/example"):
        pytest.skip("reference checkout not available")
    parsed = 0
    for rel in (
        "example/MNIST/MNIST.conf",
        "example/MNIST/MNIST_CONV.conf",
        "example/ImageNet/ImageNet.conf",
        "example/kaggle_bowl/bowl.conf",
    ):
        path = os.path.join("/root/reference", rel)
        if not os.path.exists(path):
            continue
        cfg = C.parse_file(path)
        split = C.split_sections(cfg)
        assert len(split.sections) >= 1
        assert any(n == "netconfig" and v == "start" for n, v in split.global_entries)
        parsed += 1
    assert parsed >= 1, "no reference configs were actually parsed"


# --------------------------------------------------------------------------
# property tests (hypothesis): the tokenizer must round-trip arbitrary
# well-formed key=value streams — names without separators/comments,
# values quoted when they carry spaces — and never crash on them.

_name = st.text(
    alphabet=st.characters(whitelist_categories=("Ll", "Lu", "Nd"),
                           whitelist_characters="_.:[]-"),
    min_size=1, max_size=20,
).filter(lambda s: "=" not in s and "#" not in s and not s.isspace())

_value = st.text(
    alphabet=st.characters(whitelist_categories=("Ll", "Lu", "Nd"),
                           whitelist_characters="_./,- "),
    min_size=1, max_size=30,
).filter(lambda s: s.strip() == s and s)  # no leading/trailing blanks


@settings(max_examples=200, deadline=None)
@given(st.lists(st.tuples(_name, _value), min_size=1, max_size=20))
def test_tokenizer_roundtrips_wellformed_pairs(pairs):
    text = "".join(
        (f'{n} = "{v}"\n' if " " in v else f"{n} = {v}\n")
        for n, v in pairs
    )
    assert C.parse_pairs(text) == pairs


@settings(max_examples=200, deadline=None)
@given(st.text(max_size=200))
def test_tokenizer_never_crashes_unexpectedly(text):
    """Arbitrary input either parses or raises the documented
    ConfigError — never an internal exception type."""
    try:
        out = C.parse_pairs(text)
    except C.ConfigError:
        return
    for name, val in out:
        assert isinstance(name, str) and isinstance(val, str)
