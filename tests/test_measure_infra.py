"""The measurement-infrastructure shell tools (tools/tpu_queue.sh,
tools/relay_watch.sh) — the pieces whose failure modes burned rounds
3-4 (rc=124 with no diagnostic, missed relay windows, a held flock in
the driver's bench window).  Pure-bash behavior, testable without a
relay.
"""

import os
import subprocess

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
QUEUE = os.path.join(REPO, "tools", "tpu_queue.sh")
WATCH = os.path.join(REPO, "tools", "relay_watch.sh")


def _bash(script: str, **env) -> subprocess.CompletedProcess:
    return subprocess.run(
        ["bash", "-c", script], capture_output=True, text=True,
        env={**os.environ, **{k: str(v) for k, v in env.items()}},
    )


def test_queue_refuses_when_relay_dead(tmp_path):
    """Dead relay -> rc=2 refusal in seconds, never a dial attempt.
    (Points the probe at a port nothing listens on.)"""
    r = _bash(f"bash {QUEUE} {tmp_path}/q.log", AXON_RELAY_PORT="1",
              TPU_RELAY_LOCK=str(tmp_path / "lock"))
    assert r.returncode == 2
    assert "relay dead" in r.stderr


def test_queue_deadline_gate():
    """run/sweep skip entries whose budget cannot finish before
    QUEUE_HARD_DEADLINE_EPOCH — the guard that keeps a late-window
    queue from holding the relay flock into the driver's own bench."""
    script = f"""
source /dev/stdin <<EOF
$(sed -n '/^fits_deadline/,/^}}/p; /^run()/,/^}}/p; /^sweep()/,/^}}/p' {QUEUE})
EOF
export QUEUE_HARD_DEADLINE_EPOCH=$(( $(date +%s) + 300 ))
run 1800 echo LONG
run 60 echo SHORT
sweep 900 python tools/x.py a b || true
sweep 30 true tools/y.py v1 v2 v3 || true
"""
    r = _bash(script)
    out = r.stdout
    assert "SKIP (deadline): echo LONG" in out
    assert "=== echo SHORT ===" in out
    # 900*(2+1) > 300s away -> skipped; 30*(3+1) fits -> runs
    assert "SKIP (deadline): python tools/x.py a b" in out
    assert "(n=3, per=30)" in out


def test_sweep_requires_explicit_variants():
    """n=0 would make `timeout 0` disable the external backstop
    entirely (GNU semantics) — sweep refuses instead."""
    script = f"""
source /dev/stdin <<EOF
$(sed -n '/^fits_deadline/,/^}}/p; /^sweep()/,/^}}/p' {QUEUE})
EOF
sweep 900 python tools/x_bisect.py && echo UNEXPECTED || echo REFUSED
"""
    r = _bash(script)
    assert "REFUSED" in r.stdout
    assert "list variants explicitly" in r.stderr


def test_watcher_exits_at_deadline(tmp_path):
    """A watcher started past its deadline exits without firing the
    queue (both the wait path and the outer loop check it)."""
    log = tmp_path / "w.log"
    r = _bash(
        f"bash {WATCH} {log}",
        WATCH_DEADLINE_EPOCH=1,       # 1970: always past
        AXON_RELAY_PORT="1",          # and the relay looks dead
        RELAY_WATCH_INTERVAL="1",
    )
    assert r.returncode == 0
    text = log.read_text()
    assert "deadline passed" in text
    assert "firing tpu_queue" not in text
