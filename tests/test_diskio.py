"""Disk-I/O layer tests (cxxnet_tpu/utils/diskio.py).

The recorder + ext4-reorder crash simulator that ``tools/crash_audit.py``
replays, the ENOSPC acceptance contract (a disk-full checkpoint write
aborts atomically and the prior round stays loadable; the
``disk_full_total`` alert series fires), and the torn-commit-sidecar
regression the audit pinned (a reopening ``FeedbackWriter`` must
truncate a torn ``.commit`` line before appending, or every later
commit becomes invisible).
"""

import errno
import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

from cxxnet_tpu.loop import feedback_log as fl
from cxxnet_tpu.obs import alerts as obs_alerts
from cxxnet_tpu.obs.registry import registry
from cxxnet_tpu.utils import checkpoint as ck
from cxxnet_tpu.utils import diskio, faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _blob(tag: int) -> bytes:
    import struct

    hdr = json.dumps({"round": tag}).encode()
    return ck.MODEL_MAGIC + struct.pack("<I", len(hdr)) + hdr + b"p" * 64


def _rec(val: float):
    return np.full((1, 1, 4), np.float32(val))


# ----------------------------------------------------------------------
# recorder + simulator
def test_atomic_write_never_tears_the_published_name(tmp_path):
    """At EVERY crash point of an atomic replace, the published name
    holds either the old bytes or the new bytes — in every variant."""
    path = str(tmp_path / "models" / "0001.model")
    diskio.write_atomic(path, b"OLD-CONTENT")
    with diskio.recording(str(tmp_path)) as rec:
        diskio.write_atomic(path, b"NEW-CONTENT")
    ops = rec.ops
    assert [op["op"] for op in ops if op["op"] == "rename"]
    saw_old = saw_new = False
    for k in range(len(ops) + 1):
        for variant in diskio.VARIANTS:
            for keep in ((None,) if variant != "torn" else (1, 5)):
                tree = diskio.simulate_crash(ops, k, variant,
                                             torn_keep=keep)
                if tree is None:
                    continue
                got = tree.get("models/0001.model")
                assert got in (b"OLD-CONTENT", b"NEW-CONTENT"), (
                    k, variant, got)
                saw_old |= got == b"OLD-CONTENT"
                saw_new |= got == b"NEW-CONTENT"
    assert saw_old and saw_new


def test_sync_variant_drops_unsynced_appends(tmp_path):
    """An append never fsynced is NOT durable (the file itself vanishes
    when its creation was never made durable either); an fsynced append
    survives every later crash point."""
    path = str(tmp_path / "log.bin")
    with diskio.recording(str(tmp_path)) as rec:
        diskio.append_bytes(path, b"unsynced", fsync=False)
    tree = diskio.simulate_crash(rec.ops, len(rec.ops), "sync")
    assert "log.bin" not in tree
    os.unlink(path)
    with diskio.recording(str(tmp_path)) as rec:
        diskio.append_bytes(path, b"synced!!", fsync=True)
    tree = diskio.simulate_crash(rec.ops, len(rec.ops), "sync")
    assert tree["log.bin"] == b"synced!!"


def test_torn_variant_cuts_only_the_unsynced_tail(tmp_path):
    path = str(tmp_path / "log.bin")
    with diskio.recording(str(tmp_path)) as rec:
        h = diskio.open_append(path)
        h.write(b"AAAA")
        h.fsync()
        h.write(b"BBBB")
        h.flush()
        h.close()
    ops = rec.ops
    k = len(ops)
    tree = diskio.simulate_crash(ops, k, "torn", torn_keep=2)
    assert tree["log.bin"] == b"AAAABB"
    # an fsync-covered write can never tear: crash right after the
    # first fsync has no unsynced tail -> no distinct torn state
    k_fsync = next(i for i, op in enumerate(ops)
                   if op["op"] == "fsync") + 1
    assert diskio.simulate_crash(ops, k_fsync, "torn", torn_keep=2) is None


def test_fid_follows_rename_and_unsynced_rename_rolls_back(tmp_path):
    """The fsynced temp bytes belong to the same fid after the rename;
    in the sync variant a rename without a later dir/file fsync rolls
    back to the temp name."""
    path = str(tmp_path / "f.json")
    with diskio.recording(str(tmp_path)) as rec:
        diskio.write_atomic(path, b"DATA", fsync=True)
    ops = rec.ops
    ridx = next(i for i, op in enumerate(ops) if op["op"] == "rename")
    # crash right after the rename, before the directory fsync
    tree = diskio.simulate_crash(ops, ridx + 1, "sync")
    assert "f.json" not in tree
    assert any(p.startswith(".f.json.tmp.") and data == b"DATA"
               for p, data in tree.items())
    # after the dir fsync the published name is durable
    tree = diskio.simulate_crash(ops, len(ops), "sync")
    assert tree["f.json"] == b"DATA"


def test_preexisting_files_survive_every_crash_state(tmp_path):
    keep = tmp_path / "keep.txt"
    keep.write_bytes(b"precious")
    with diskio.recording(str(tmp_path)) as rec:
        diskio.unlink(str(tmp_path / "keep.txt"))
    ops = rec.ops
    # before the unlink op every variant still holds the snapshot
    k = next(i for i, op in enumerate(ops) if op["op"] == "unlink")
    for variant in ("flush", "sync"):
        assert diskio.simulate_crash(ops, k, variant)["keep.txt"] \
            == b"precious"
    # the unlink was never made durable (no dir fsync): sync resurrects
    assert diskio.simulate_crash(
        ops, len(ops), "sync")["keep.txt"] == b"precious"
    assert "keep.txt" not in diskio.simulate_crash(
        ops, len(ops), "flush")


def test_marks_ride_the_journal(tmp_path):
    with diskio.recording(str(tmp_path)) as rec:
        diskio.append_bytes(str(tmp_path / "a"), b"x", fsync=True)
        diskio.mark("committed", seqs=[1, 2])
        diskio.append_bytes(str(tmp_path / "b"), b"y", fsync=True)
    ops = rec.ops
    midx = next(i for i, op in enumerate(ops) if op["op"] == "mark")
    assert diskio.marks_before(ops, midx) == []
    after = diskio.marks_before(ops, len(ops))
    assert after == [{"op": "mark", "name": "committed", "seqs": [1, 2]}]
    # marks never materialize as files
    assert set(diskio.simulate_crash(ops, len(ops), "flush")) == {"a", "b"}


def test_one_recording_per_process(tmp_path):
    with diskio.recording(str(tmp_path)):
        with pytest.raises(RuntimeError, match="already active"):
            with diskio.recording(str(tmp_path)):
                pass
    assert diskio.recorder() is None


def test_kill_hook_sigkills_before_the_matching_op(tmp_path):
    """CXXNET_DISKIO_KILL_AT lands SIGKILL before the nth matching
    durable op (subprocess: the hook kills the whole process)."""
    script = (
        "import sys; sys.path.insert(0, sys.argv[1])\n"
        "from cxxnet_tpu.utils import diskio\n"
        "diskio.write_atomic(sys.argv[2] + '/one.model', b'1')\n"
        "diskio.write_atomic(sys.argv[2] + '/two.model', b'2')\n"
        "print('SURVIVED')\n"
    )
    env = dict(os.environ, CXXNET_DISKIO_KILL_AT="two.model",
               JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-c", script, REPO, str(tmp_path)],
        env=env, capture_output=True, text=True, timeout=120)
    assert out.returncode == -signal.SIGKILL
    assert "SURVIVED" not in out.stdout
    # the op BEFORE the matching one completed; the matching one never
    # published (the kill fires before the temp write)
    assert (tmp_path / "one.model").read_bytes() == b"1"
    assert not (tmp_path / "two.model").exists()


# ----------------------------------------------------------------------
# ENOSPC acceptance: abort atomically, stay loadable, page the operator
def _disk_full_count(site: str) -> float:
    return registry().counter(
        "disk_full_total", "", labelnames=("site",)
    ).labels(site=site).value


@pytest.mark.parametrize("kind", ["enospc", "short"])
def test_checkpoint_disk_full_aborts_atomically(tmp_path, kind):
    mdir = str(tmp_path / "models")
    ck.write_checkpoint(ck.publish_path(mdir, 1), _blob(1), round_=1)
    before = _disk_full_count("checkpoint.write")
    faults.install(f"checkpoint.write:{kind}:1")
    try:
        with pytest.raises(OSError) as ei:
            ck.write_checkpoint(ck.publish_path(mdir, 2), _blob(2),
                                round_=2)
        assert ei.value.errno == errno.ENOSPC
    finally:
        faults.reset()
    assert _disk_full_count("checkpoint.write") > before
    # atomic abort: no round-2 artifact, no temp litter, round 1 loads
    assert not os.path.exists(ck.publish_path(mdir, 2))
    assert not [n for n in os.listdir(mdir) if ".tmp." in n]
    latest = ck.find_latest_valid(mdir, silent=True)
    assert latest is not None and latest[0] == 1
    assert ck.validate_checkpoint(latest[1]) is None


def test_disk_full_alert_fires_on_rate():
    """The operator contract: any ENOSPC hit moves ``disk_full_rate``
    off zero, and a ``:>:0`` rule on it fires on the next evaluation."""
    ev = obs_alerts.AlertEvaluator()
    ev.add_rule(obs_alerts.parse_rule("disk_full:disk_full_rate:>:0"))
    ev.evaluate_once(now=100.0)
    diskio.count_disk_full("checkpoint.write", "/models/0001.model")
    emitted = ev.evaluate_once(now=102.0)
    assert any(e["kind"] == "alert.firing" and e["name"] == "disk_full"
               for e in emitted)
    assert ev.firing() == ["disk_full"]


def test_feedback_append_survives_disk_full(tmp_path):
    """Serving contract: ENOSPC on the feedback path drops the page,
    counts it, and keeps accepting appends — it never raises into the
    predict handler."""
    w = fl.FeedbackWriter(str(tmp_path), page_bytes=1 << 20,
                          rotate_bytes=1 << 20, fsync=True)
    before = _disk_full_count("loop.commit")
    assert w.append(_rec(1.0), [1.0]) == 1
    faults.install("loop.commit:enospc:1:1")
    try:
        assert w.flush() == 0  # page dropped, no raise
    finally:
        faults.reset()
    assert w.dropped == 1
    assert _disk_full_count("loop.commit") > before
    # the writer keeps working once the disk clears
    assert w.append(_rec(2.0), [2.0]) == 1
    assert w.flush() == 1
    w.close()
    recs, _ = fl.FeedbackReader(str(tmp_path)).read_since()
    assert [float(r.labels[0]) for r in recs] == [2.0]


# ----------------------------------------------------------------------
# the torn-commit-sidecar regression (crash-audit corpus, pinned)
def test_reopen_truncates_torn_commit_sidecar(tmp_path):
    d = str(tmp_path)
    w = fl.FeedbackWriter(d, page_bytes=1 << 20, rotate_bytes=1 << 20,
                          fsync=True, drop_on_error=False)
    s1 = w.append_seq(_rec(1.0), [1.0])
    w.flush()
    s2 = w.append_seq(_rec(2.0), [2.0])
    w.flush()
    w.close()
    cpath = os.path.join(d, "feedback-000000.bin" + fl.COMMIT_SUFFIX)
    with open(cpath, "rb") as f:
        raw = f.read()
    first_end = raw.index(b"\n") + 1
    torn = raw[: first_end + (len(raw) - first_end) // 2]
    with open(cpath, "wb") as f:
        f.write(torn)  # second commit line torn mid-record, no newline
    # parsing stops at the clean length: one commit, page 2 uncommitted
    ents, clean_len = fl._read_commits_full(
        os.path.join(d, "feedback-000000.bin"))
    assert len(ents) == 1 and clean_len == first_end
    # reopen MUST truncate the torn line before appending: without it
    # the next entry fuses onto the partial line and every later commit
    # is unparseable (committed records silently lost)
    w = fl.FeedbackWriter(d, page_bytes=1 << 20, rotate_bytes=1 << 20,
                          fsync=True, drop_on_error=False)
    assert os.path.getsize(cpath) == first_end
    s3 = w.append_seq(_rec(3.0), [3.0])
    w.flush()
    w.close()
    got = {r.seq: float(r.labels[0])
           for r in fl.FeedbackReader(d).read_since()[0]}
    assert got[s1] == 1.0
    assert s2 not in got  # torn page stays uncommitted
    assert got[s3] == 3.0  # the new commit is visible
    # lineage: the torn page's id is burned, never reused
    assert s3 > s2


# ----------------------------------------------------------------------
# the auditor itself stays green (fast single-workload pass)
def test_crash_audit_checkpoint_workload_clean(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import crash_audit
    finally:
        sys.path.pop(0)
    out = str(tmp_path / "verdict.json")
    assert crash_audit.main(["--only", "checkpoint", "--out", out]) == 0
    doc = json.load(open(out))
    assert doc["violations"] == []
    assert doc["workloads"]["checkpoint"]["distinct"] > 50
