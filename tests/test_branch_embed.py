"""Branch-embedding fusion (``conv_branch_embed = 1``).

The inception 3x3/5x5 branch convs run as ONE block-kernel conv
(doc/performance.md "Conv efficiency"; the cuDNN algorithmic-rewrite
analog, ``/root/reference/src/layer/cudnn_convolution_layer-inl.hpp``).
Exactness at the op level, end-to-end pair equality on GoogLeNet (which
also exercises the deferred-consumer rescheduling — the 5x5 reduce sits
between the 3x3 conv and the 5x5 conv in declaration order), training
parity, SPMD composition, and the off-domain no-op.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cxxnet_tpu import config as C
from cxxnet_tpu.nnet.net import FunctionalNet
from cxxnet_tpu.nnet.trainer import NetTrainer


def _int_valued(rng, *shape):
    # integer-valued f32: conv sums stay < 2^24, so equality is exact
    return jnp.asarray(
        rng.randint(-3, 4, shape).astype(np.float32))


def test_apply_branch_embed_bit_exact():
    """The block-kernel conv equals the separate member convs bit-for-
    bit on integer-valued inputs (no float-tolerance hiding)."""
    from jax import lax

    rng = np.random.RandomState(0)
    x3 = _int_valued(rng, 2, 9, 9, 6)
    x5 = _int_valued(rng, 2, 9, 9, 4)
    w3 = _int_valued(rng, 3, 3, 6, 8)
    w5 = _int_valued(rng, 5, 5, 4, 3)
    b3 = _int_valued(rng, 8)
    b5 = _int_valued(rng, 3)
    y3 = lax.conv_general_dilated(
        x3, w3, (1, 1), ((1, 1), (1, 1)),
        dimension_numbers=("NHWC", "HWIO", "NHWC")) + b3
    y5 = lax.conv_general_dilated(
        x5, w5, (1, 1), ((2, 2), (2, 2)),
        dimension_numbers=("NHWC", "HWIO", "NHWC")) + b5
    o3, o5 = FunctionalNet._apply_branch_embed(
        [{"wmat": w3, "bias": b3}, {"wmat": w5, "bias": b5}], [x3, x5])
    np.testing.assert_array_equal(np.asarray(o3), np.asarray(y3))
    np.testing.assert_array_equal(np.asarray(o5), np.asarray(y5))


INCEPTION_CFG = [
    ("dev", "tpu:0-{n}"),
    ("batch_size", "16"),
    ("input_shape", "8,12,12"),
    ("eta", "0.1"),
    ("momentum", "0.9"),
    ("netconfig", "start"),
    # branch A: 1x1 reduce -> relu -> 3x3
    ("layer[0->1]", "conv:r3"),
    ("kernel_size", "1"), ("pad", "0"), ("nchannel", "6"),
    ("random_type", "xavier"),
    ("layer[1->2]", "relu"),
    ("layer[2->3]", "conv:c3"),
    ("kernel_size", "3"), ("pad", "1"), ("nchannel", "8"),
    ("random_type", "xavier"),
    ("layer[3->4]", "relu"),
    # branch B: 1x1 reduce -> relu -> 5x5 (declared AFTER c3: the
    # rescheduling path — c5's input does not exist at c3's position)
    ("layer[0->5]", "conv:r5"),
    ("kernel_size", "1"), ("pad", "0"), ("nchannel", "4"),
    ("random_type", "xavier"),
    ("layer[5->6]", "relu"),
    ("layer[6->7]", "conv:c5"),
    ("kernel_size", "5"), ("pad", "2"), ("nchannel", "4"),
    ("random_type", "xavier"),
    ("layer[7->8]", "relu"),
    ("layer[4,8->9]", "ch_concat"),
    ("layer[9->10]", "flatten"),
    ("layer[10->11]", "fullc:fc"),
    ("nhidden", "4"), ("random_type", "xavier"),
    ("layer[11->11]", "softmax"),
    ("netconfig", "end"),
]


def _build(bembed, ndev=1, extra=()):
    cfg = [(k, v.format(n=ndev - 1) if k == "dev" else v)
           for k, v in INCEPTION_CFG]
    tr = NetTrainer()
    tr.set_params(cfg + [("conv_branch_embed", str(bembed)),
                         ("seed", "11")] + list(extra))
    tr.init_model()
    return tr


def test_inception_group_forms_and_reschedules():
    tr = _build(1)
    items, gmap = tr.net._branch_embed_plan()
    assert items is not None
    # one group: the c3 (idx 2 in layer list terms) + c5 convs
    (leader, idxs), = gmap.items()
    assert len(idxs) == 2
    names = [tr.net.graph.layers[j].name for j in idxs]
    assert names == ["c3", "c5"]
    # the plan runs every layer exactly once, members only via the group
    ran = [i for kind, i in items if kind == "L"]
    assert sorted(ran + list(idxs)) == list(range(len(tr.net.graph.layers)))
    # c5's reduce chain (r5, relu) must execute before the group
    pos = {("E" if k == "E" else i): n for n, (k, i) in enumerate(items)}
    r5_idx = next(j for j, s in enumerate(tr.net.graph.layers)
                  if s.name == "r5")
    assert pos[r5_idx] < pos["E"]


def test_inception_pair_forward_and_grads():
    """conv_branch_embed=1 equals the plain path: loss and every
    gradient (same seed -> same init), wino-test tolerances (the f32
    delta is XLA conv-lowering reassociation; f64 is bit-exact)."""
    a, b = _build(0), _build(1)
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(16, 12, 12, 8).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 4, (16, 1)).astype(np.float32))
    la = a.net.loss_fn(a.params, x, y, train=False)
    lb = b.net.loss_fn(b.params, x, y, train=False)
    np.testing.assert_allclose(float(la), float(lb), rtol=2e-4)
    ga = jax.grad(lambda p: a.net.loss_fn(p, x, y, train=False))(a.params)
    gb = jax.grad(lambda p: b.net.loss_fn(p, x, y, train=False))(b.params)
    for pa, pb in zip(jax.tree_util.tree_leaves(ga),
                      jax.tree_util.tree_leaves(gb)):
        np.testing.assert_allclose(np.asarray(pa), np.asarray(pb),
                                   rtol=2e-3, atol=2e-3)


def test_googlenet_all_nine_modules_group():
    """The real GoogLeNet builder conf: all 9 inception modules form a
    (3x3, 5x5) group, and the fused net's loss matches the plain one."""
    from cxxnet_tpu.models import googlenet_conf

    def build(bembed):
        tr = NetTrainer()
        tr.set_params(C.parse_pairs(googlenet_conf(
            batch_size=4, num_class=10, synthetic=False, dev="cpu",
            input_size=64)))
        tr.set_param("conv_branch_embed", str(bembed))
        tr.set_param("seed", "7")
        tr.init_model()
        return tr

    a, b = build(0), build(1)
    _items, gmap = b.net._branch_embed_plan()
    assert len(gmap) == 9
    assert all(len(v) == 2 for v in gmap.values())
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(4, 64, 64, 3).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 10, (4, 1)).astype(np.float32))
    la = float(a.net.loss_fn(a.params, x, y, train=False))
    lb = float(b.net.loss_fn(b.params, x, y, train=False))
    np.testing.assert_allclose(la, lb, rtol=1e-3)


def test_branch_embed_training_parity():
    """3 sgd+momentum steps with the fusion on vs off stay within the
    SPMD-parity tolerance — the gradient path through the block kernel
    is the same optimization trajectory."""
    ta, tb = _build(0), _build(1)
    rng = np.random.RandomState(5)
    for _ in range(3):
        x = rng.randn(16, 12, 12, 8).astype(np.float32)
        y = rng.randint(0, 4, (16, 1)).astype(np.float32)
        ta.update_all(x, y)
    rng = np.random.RandomState(5)
    for _ in range(3):
        x = rng.randn(16, 12, 12, 8).astype(np.float32)
        y = rng.randint(0, 4, (16, 1)).astype(np.float32)
        tb.update_all(x, y)
    for key in ta.params:
        for tag in ta.params[key]:
            np.testing.assert_allclose(
                np.asarray(ta.params[key][tag]),
                np.asarray(tb.params[key][tag]),
                rtol=2e-3, atol=2e-4,
                err_msg=f"{key}/{tag} diverged (branch-embed on vs off)",
            )


@pytest.mark.parametrize("mp", [1, 2])
def test_branch_embed_matches_single_under_mesh(mp):
    """Composes with DP (and DP x TP) sharding over the 8-device mesh,
    the same discipline as the wino/s2d SPMD parity tests."""
    def train(ndev):
        tr = _build(1, ndev=ndev,
                    extra=([("model_parallel", str(mp))]
                           if ndev > 1 else []))
        rng = np.random.RandomState(5)
        for _ in range(3):
            tr.update_all(rng.randn(16, 12, 12, 8).astype(np.float32),
                          rng.randint(0, 4, (16, 1)).astype(np.float32))
        return tr

    t1, t8 = train(1), train(8)
    assert t8.net._branch_embed_plan()[1]
    for key in t1.params:
        for tag in t1.params[key]:
            np.testing.assert_allclose(
                np.asarray(t1.params[key][tag]),
                np.asarray(t8.params[key][tag]),
                rtol=2e-4, atol=2e-5,
                err_msg=f"{key}/{tag} diverged (1- vs 8-device)",
            )


def test_branch_embed_off_domain_no_group():
    """Strided / non-SAME / lone convs never group: ResNet-50 and
    AlexNet plans stay empty (the knob is inception-shaped by
    construction)."""
    from cxxnet_tpu.models import alexnet_conf, resnet50_conf

    for conf in (resnet50_conf(batch_size=4, num_class=10,
                               synthetic=False, dev="cpu", input_size=32),
                 alexnet_conf(batch_size=4, num_class=10,
                              synthetic=False, dev="cpu", input_size=67)):
        tr = NetTrainer()
        tr.set_params(C.parse_pairs(conf))
        tr.set_param("conv_branch_embed", "1")
        tr.init_model()
        items, gmap = tr.net._branch_embed_plan()
        assert gmap == {} and items is None


def test_branch_embed_checkpoint_interchange(tmp_path):
    """Parameters stay per-layer under the fusion: a checkpoint saved
    from a bembed-trained net loads into a plain net (and back) with
    identical predictions — the fusion is execution-only state."""
    ta = _build(1)
    rng = np.random.RandomState(9)
    x = rng.randn(16, 12, 12, 8).astype(np.float32)
    y = rng.randint(0, 4, (16, 1)).astype(np.float32)
    ta.update_all(x, y)
    p = str(tmp_path / "be.model")
    ta.save_model(p)
    tb = _build(0)
    tb.load_model(p)
    xa = jnp.asarray(x)
    na, _ = ta.net.forward(ta.params, xa, train=False)
    nb, _ = tb.net.forward(tb.params, xa, train=False)
    np.testing.assert_allclose(
        np.asarray(na[ta.net.out_node_index()]),
        np.asarray(nb[tb.net.out_node_index()]), rtol=2e-4, atol=2e-5)


def test_branch_embed_update_scan():
    """The device-side scanned step (update_scan) runs the same
    forward; a scanned round with the fusion on matches per-step
    updates with it off within the SPMD-parity tolerance."""
    ta, tb = _build(1), _build(0)
    rng = np.random.RandomState(13)
    xs = rng.randn(4, 16, 12, 12, 8).astype(np.float32)
    ys = rng.randint(0, 4, (4, 16, 1)).astype(np.float32)
    ta.update_scan(xs, ys)
    from cxxnet_tpu.io.data import DataBatch

    for k in range(4):
        tb.update(DataBatch(data=xs[k], label=ys[k]))
    for key in ta.params:
        for tag in ta.params[key]:
            np.testing.assert_allclose(
                np.asarray(ta.params[key][tag]),
                np.asarray(tb.params[key][tag]),
                rtol=2e-3, atol=2e-4,
                err_msg=f"{key}/{tag} diverged (scan+embed vs plain)",
            )


def test_branch_embed_with_remat_and_bf16():
    """Smoke: composes with jax.checkpoint and compute_dtype=bfloat16
    (the two knobs most likely to interact with a custom apply path)."""
    tr = _build(1, extra=[("remat", "1"),
                          ("compute_dtype", "bfloat16")])
    rng = np.random.RandomState(2)
    x = rng.randn(16, 12, 12, 8).astype(np.float32)
    y = rng.randint(0, 4, (16, 1)).astype(np.float32)
    tr.update_all(x, y)
    assert np.isfinite(
        np.asarray(tr.params["l2_c3"]["wmat"]).sum())
