"""Native C++ IO pipeline: page reader + JPEG decode pool.

Builds ``native/libcxxnet_io.so`` on demand; asserts the native path
yields the same records, in the same (.lst) order, as the pure-Python
path — the PairTest discipline (SURVEY §4.1) applied to the IO stack.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from cxxnet_tpu.io.imgbin import (
    BinPageWriter,
    ImageBinIterator,
    decode_image,
    iter_bin_pages,
)
from cxxnet_tpu.io import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native IO library unavailable"
)


def _make_jpegs(tmp_path, n=12, seed=0):
    from PIL import Image

    rng = np.random.RandomState(seed)
    blobs = []
    for i in range(n):
        arr = rng.randint(0, 255, size=(24 + i, 32, 3), dtype=np.uint8)
        import io as _io

        buf = _io.BytesIO()
        Image.fromarray(arr).save(buf, format="JPEG", quality=92)
        blobs.append(buf.getvalue())
    return blobs


def _pack(tmp_path, blobs, name="part0"):
    bin_path = str(tmp_path / f"{name}.bin")
    lst_path = str(tmp_path / f"{name}.lst")
    w = BinPageWriter(bin_path, page_size=4096)  # force multiple pages
    for b in blobs:
        w.push(b)
    w.close()
    with open(lst_path, "w") as f:
        for i in range(len(blobs)):
            f.write(f"{i}\t{i % 5}\timg{i}.jpg\n")
    return bin_path, lst_path


def test_native_reader_matches_python(tmp_path):
    blobs = _make_jpegs(tmp_path)
    bin_path, _ = _pack(tmp_path, blobs)
    # python side
    py = [b for page in iter_bin_pages(bin_path) for b in page]
    assert py == blobs
    # native side: same order, decoded
    r = native.NativePageReader([bin_path], n_decode=3)
    for i, blob in enumerate(blobs):
        rec = r.next()
        assert rec is not None, f"native reader ended early at {i}"
        kind, payload = rec
        assert kind == 1
        ref = decode_image(blob)
        assert payload.shape == ref.shape
        # PIL and libjpeg share the same decoder; allow ±1 for rounding
        assert np.abs(payload.astype(np.int16) - ref.astype(np.int16)).max() <= 1
    assert r.next() is None
    # reset replays from the start
    r.reset()
    rec = r.next()
    assert rec is not None and rec[1].shape == decode_image(blobs[0]).shape
    r.close()


def test_native_reader_non_jpeg_passthrough(tmp_path):
    blobs = [b"not-a-jpeg-blob-%d" % i for i in range(4)]
    bin_path = str(tmp_path / "raw.bin")
    w = BinPageWriter(bin_path, page_size=4096)
    for b in blobs:
        w.push(b)
    w.close()
    r = native.NativePageReader([bin_path], n_decode=2)
    got = []
    while (rec := r.next()) is not None:
        kind, payload = rec
        assert kind == 0
        got.append(payload)
    assert got == blobs
    r.close()


def test_imgbin_iterator_uses_native(tmp_path):
    blobs = _make_jpegs(tmp_path, n=8)
    bin_path, lst_path = _pack(tmp_path, blobs)
    it = ImageBinIterator()
    it.set_param("image_bin", bin_path)
    it.set_param("image_list", lst_path)
    it.init()
    assert it._native is not None, "native decoder should engage"
    seen = 0
    while it.next():
        inst = it.value()
        assert inst.index == seen
        assert inst.data.shape == decode_image(blobs[seen]).shape
        seen += 1
    assert seen == len(blobs)
    # epoch 2
    it.before_first()
    assert it.next() and it.value().index == 0


def test_imgbin_iterator_python_fallback_matches(tmp_path):
    blobs = _make_jpegs(tmp_path, n=6)
    bin_path, lst_path = _pack(tmp_path, blobs)

    def run(native_flag):
        it = ImageBinIterator()
        it.set_param("image_bin", bin_path)
        it.set_param("image_list", lst_path)
        it.set_param("native_decoder", str(native_flag))
        it.init()
        out = []
        while it.next():
            out.append(np.asarray(it.value().data))
        return out

    a = run(1)
    b = run(0)
    assert len(a) == len(b) == len(blobs)
    for x, y in zip(a, b):
        assert np.abs(x.astype(np.int16) - y.astype(np.int16)).max() <= 1
