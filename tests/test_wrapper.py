"""Python API parity tests: DataIter / Net / train (wrapper/cxxnet.py)."""

import os

import numpy as np
import pytest

from cxxnet_tpu.wrapper import DataIter, Net, train

MLP_CFG = """
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 32
  init_sigma = 0.1
layer[+1:a1] = relu:a1
layer[a1->out] = fullc:fc2
  nhidden = 4
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,8
batch_size = 16
eta = 0.5
momentum = 0.9
metric = error
"""


def toy_xy(n=64, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 8).astype(np.float32)
    w = rng.randn(8, 4).astype(np.float32)
    y = (x @ w).argmax(-1).astype(np.float32)
    return x, y


def csv_iter(tmp_path, x, y, name="train.csv", batch=16):
    path = os.path.join(str(tmp_path), name)
    rows = np.concatenate([y[:, None], x], axis=1)
    np.savetxt(path, rows, delimiter=",")
    return DataIter(
        f"""
        iter = csv
        filename = {path}
        label_width = 1
        input_shape = 1,1,8
        batch_size = {batch}
        """
    )


def test_dataiter_protocol(tmp_path):
    x, y = toy_xy(32)
    it = csv_iter(tmp_path, x, y)
    with pytest.raises(RuntimeError):
        it.get_data()  # head state
    assert it.next()
    d, l = it.get_data(), it.get_label()
    assert d.reshape(16, 8).shape == (16, 8) and l.shape == (16, 1)
    np.testing.assert_allclose(d.reshape(16, 8), x[:16], rtol=1e-5)
    assert it.next()
    assert not it.next()
    with pytest.raises(RuntimeError):
        it.get_data()  # tail state
    it.before_first()
    assert it.next()


def test_dataiter_section_markers_tolerated(tmp_path):
    x, y = toy_xy(16)
    path = os.path.join(str(tmp_path), "t.csv")
    np.savetxt(path, np.concatenate([y[:, None], x], 1), delimiter=",")
    it = DataIter(
        f"""
        data = train
        iter = csv
        filename = {path}
        label_width = 1
        input_shape = 1,1,8
        batch_size = 16
        iter = end
        """
    )
    assert it.next()
    assert it.get_data().shape[0] == 16


def test_net_update_ndarray_and_predict():
    net = Net(dev="cpu", cfg=MLP_CFG)
    net.init_model()
    x, y = toy_xy(64)
    for _ in range(60):
        for i in range(0, 64, 16):
            net.update(x[i : i + 16], y[i : i + 16])
    pred = net.predict(x[:16])
    assert pred.shape == (16,)
    assert (pred == y[:16]).mean() >= 0.9


def test_net_update_label_validation():
    net = Net(dev="cpu", cfg=MLP_CFG)
    net.init_model()
    x, y = toy_xy(16)
    with pytest.raises(ValueError):
        net.update(x)  # no label
    with pytest.raises(ValueError):
        net.update(x, y[:8])  # size mismatch
    with pytest.raises(TypeError):
        net.update([1, 2, 3], y)


def test_net_weight_roundtrip_and_extract():
    net = Net(dev="cpu", cfg=MLP_CFG)
    net.init_model()
    w = net.get_weight("fc1", "wmat")
    assert w is not None and w.size > 0
    net.set_weight(np.zeros_like(w), "fc1", "wmat")
    assert np.all(net.get_weight("fc1", "wmat") == 0)
    assert net.get_weight("a1", "wmat") is None  # no-weight layer
    x, _ = toy_xy(16)
    feat = net.extract(x, "fc1")
    assert feat.shape[0] == 16 and feat.reshape(16, -1).shape[1] == 32
    top = net.extract(x, "top[-1]")
    assert top.reshape(16, -1).shape[1] == 4


def test_net_save_load_model(tmp_path):
    net = Net(dev="cpu", cfg=MLP_CFG)
    net.init_model()
    x, y = toy_xy(32)
    net.update(x[:16], y[:16])
    path = os.path.join(str(tmp_path), "m.model")
    net.save_model(path)
    net2 = Net(dev="cpu", cfg=MLP_CFG)
    net2.load_model(path)
    np.testing.assert_allclose(
        net.get_weight("fc1", "wmat"), net2.get_weight("fc1", "wmat")
    )
    np.testing.assert_allclose(net.predict(x[:16]), net2.predict(x[:16]))


def test_train_loop_with_iterators(tmp_path, capsys):
    x, y = toy_xy(64)
    it = csv_iter(tmp_path, x, y)
    ev = csv_iter(tmp_path, x[:32], y[:32], name="eval.csv")
    net = train(
        MLP_CFG,
        it,
        num_round=40,
        param={"eta": 0.5},
        eval_data=ev,
        dev="cpu",
        print_step=0,
    )
    ev.before_first()
    assert ev.next()
    pred = net.predict(ev)
    assert (pred == y[:16]).mean() >= 0.9
    captured = capsys.readouterr()
    assert "eval-error" in captured.err


def test_train_loop_with_ndarray():
    x, y = toy_xy(16)
    net = train(MLP_CFG, x, num_round=3, param={}, label=y, dev="cpu")
    assert net.trainer.epoch_counter == 3


def test_load_model_without_conf_errors_clearly(tmp_path):
    """Checkpoints are structure-only (reference parity): loading into a
    bare Net must say so instead of failing deep in shape inference."""
    import pytest

    from cxxnet_tpu.wrapper import Net

    conf = """
netconfig = start
layer[0->1] = fullc:fc
  nhidden = 4
layer[1->1] = softmax
netconfig = end
input_shape = 1,1,8
batch_size = 4
eta = 0.1
"""
    net = Net(dev="cpu", cfg=conf)
    net.init_model()
    net.save_model(str(tmp_path / "m.model"))
    bare = Net(dev="cpu")
    with pytest.raises(ValueError, match="netconfig"):
        bare.load_model(str(tmp_path / "m.model"))


def test_predict_ndarray_trims_to_request_rows():
    """Raw-array predict/extract must return exactly the requested rows
    (bucket padding trimmed) and match the full-batch rows bit-exactly."""
    net = Net(dev="cpu", cfg=MLP_CFG)
    net.init_model()
    x, _ = toy_xy(32)
    full = net.predict(x)
    full_feat = net.extract(x, "fc1")
    for n in (1, 3, 7, 20):
        pred = net.predict(x[:n])
        assert pred.shape == (n,)
        np.testing.assert_array_equal(pred, full[:n])
        feat = net.extract(x[:n], "fc1")
        assert feat.shape[0] == n
        np.testing.assert_array_equal(feat, full_feat[:n])


def test_predict_ndarray_bucket_cache_no_rejit():
    """Repeated odd-sized raw-array calls hit the shape-bucket cache
    instead of re-tracing a fresh XLA program per size (forward runs
    only at trace time, so its call count == compile count)."""
    net = Net(dev="cpu", cfg=MLP_CFG)
    net.init_model()
    x, _ = toy_xy(64)
    calls = []
    orig = net.trainer.net.forward

    def counting(*a, **k):
        calls.append(1)
        return orig(*a, **k)

    net.trainer.net.forward = counting
    sizes = [1, 3, 7, 5, 3, 1, 7, 6, 2, 5]
    for n in sizes:
        assert net.predict(x[:n]).shape == (n,)
    # buckets {1, 2, 4, 8}: at most one trace per bucket, none repeated
    assert len(calls) <= len({1, 2, 4, 8})
    warm = len(calls)
    for n in sizes:
        net.predict(x[:n])
    assert len(calls) == warm, "odd-sized predict re-jitted after warmup"


def test_net_update_scan_trains_like_update():
    # [K, B, ...] stack path: 4 chunks of 16 per epoch as one dispatch
    net = Net(dev="cpu", cfg=MLP_CFG)
    net.init_model()
    x, y = toy_xy(64)
    stack = x.reshape(4, 16, -1)
    lstack = y.reshape(4, 16, -1)
    losses = None
    for _ in range(60):
        losses = net.update_scan(stack, lstack)
    assert losses.shape == (4,)
    pred = net.predict(x[:16])
    assert (pred == y[:16]).mean() >= 0.9
