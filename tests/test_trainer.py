"""NetTrainer tests: overfit, accumulation, checkpointing, finetune, weights."""

import jax
import numpy as np
import pytest

from cxxnet_tpu import config as C
from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.nnet.trainer import NetTrainer

MLP_CFG = """
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 32
  init_sigma = 0.1
layer[+1:a1] = relu
layer[a1->out] = fullc:fc2
  nhidden = 4
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,8
batch_size = 16
eta = 0.5
momentum = 0.9
metric = error
metric = logloss
"""


def make_trainer(extra=""):
    tr = NetTrainer()
    tr.set_params(C.parse_pairs(MLP_CFG + extra))
    tr.init_model()
    return tr


def toy_data(n=64, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 8).astype(np.float32)
    w = rng.randn(8, 4).astype(np.float32)
    y = (x @ w).argmax(-1).astype(np.float32)[:, None]
    return x, y


def batches(x, y, bs=16):
    for i in range(0, len(x), bs):
        yield DataBatch(data=x[i : i + bs], label=y[i : i + bs])


def test_overfit_small_dataset():
    tr = make_trainer()
    x, y = toy_data()
    first_err = None
    for epoch in range(60):
        for b in batches(x, y):
            tr.update(b)
    # final train error on the data itself
    errs = []
    for b in batches(x, y):
        pred = tr.predict(b)
        errs.append((pred != b.label[:, 0]).mean())
    err = float(np.mean(errs))
    assert err <= 0.05, f"did not overfit: err={err}"
    assert tr.epoch_counter == 60 * 4


def test_update_period_accumulation():
    tr = make_trainer("update_period = 2\n")
    x, y = toy_data(32)
    for b in batches(x, y):
        tr.update(b)
    # 2 micro-batches per update → epoch_counter advanced half as often
    assert tr.epoch_counter == 1
    assert tr.sample_counter == 0


def test_eval_train_metrics_and_format():
    tr = make_trainer()
    x, y = toy_data(32)
    for b in batches(x, y):
        tr.update(b)
    line = tr.evaluate(None, "train")
    assert "\ttrain-error:" in line and "\ttrain-logloss:" in line


def test_evaluate_iterator_trims_padding():
    from cxxnet_tpu.utils.metric import MetricSet

    tr = make_trainer()
    x, y = toy_data(32)

    class FakeIter:
        def __init__(self):
            self.pos = 0

        def before_first(self):
            self.pos = 0

        def next(self):
            self.pos += 1
            return self.pos <= 2

        def value(self):
            b = DataBatch(data=x[:16], label=y[:16])
            if self.pos == 2:
                b = DataBatch(data=x[16:32], label=y[16:32], num_batch_padd=6)
            return b

    line = tr.evaluate(FakeIter(), "val")
    assert "\tval-error:" in line
    # 16 + 10 = 26 instances counted
    assert tr.metric.metrics[0].cnt_inst == 26


def test_checkpoint_roundtrip(tmp_path):
    tr = make_trainer()
    x, y = toy_data(32)
    for b in batches(x, y):
        tr.update(b)
    path = str(tmp_path / "0001.model")
    tr.save_model(path)

    tr2 = NetTrainer()
    tr2.set_params(C.parse_pairs(MLP_CFG))
    tr2.load_model(path)
    assert tr2.epoch_counter == tr.epoch_counter
    b = DataBatch(data=x[:16], label=y[:16])
    np.testing.assert_allclose(tr.predict(b), tr2.predict(b))
    # loaded model can continue training
    tr2.update(b)


def test_finetune_copies_matched_layers(tmp_path):
    tr = make_trainer()
    path = str(tmp_path / "m.model")
    tr.save_model(path)

    # new net: same fc1 name, different fc2 size → only fc1 copied
    cfg2 = MLP_CFG.replace("nhidden = 4", "nhidden = 3")
    tr2 = NetTrainer()
    tr2.set_params(C.parse_pairs(cfg2))
    tr2.copy_model_from(path)
    np.testing.assert_allclose(
        tr2.get_weight("fc1", "wmat"), tr.get_weight("fc1", "wmat")
    )
    assert tr2.get_weight("fc2", "wmat").shape == (3, 32)
    assert tr2.epoch_counter == 0


def test_get_set_weight_2d():
    tr = make_trainer()
    w = tr.get_weight("fc1", "wmat")
    assert w.shape == (32, 8)
    neww = np.zeros_like(w)
    tr.set_weight(neww, "fc1", "wmat")
    np.testing.assert_allclose(tr.get_weight("fc1", "wmat"), 0.0)
    b = tr.get_weight("fc1", "bias")
    assert b.shape == (1, 32)


def test_conv_weight_2d_roundtrip():
    cfg = """
netconfig=start
layer[0->1] = conv:cv
  kernel_size = 3
  nchannel = 6
netconfig=end
input_shape = 3,8,8
batch_size = 4
"""
    tr = NetTrainer()
    tr.set_params(C.parse_pairs(cfg))
    tr.init_model()
    w2 = tr.get_weight("cv", "wmat")
    assert w2.shape == (6, 3 * 3 * 3)
    tr.set_weight(w2 * 2, "cv", "wmat")
    np.testing.assert_allclose(tr.get_weight("cv", "wmat"), w2 * 2, rtol=1e-6)


def test_predict_raw_single_column():
    cfg = """
netconfig=start
layer[0->1] = fullc:f
  nhidden = 1
layer[+0] = l2_loss
netconfig=end
input_shape = 1,1,4
batch_size = 8
"""
    tr = NetTrainer()
    tr.set_params(C.parse_pairs(cfg))
    tr.init_model()
    x = np.ones((8, 4), np.float32)
    pred = tr.predict(DataBatch(data=x, label=np.zeros((8, 1), np.float32)))
    # 1-column output: raw values, not argmax
    w = tr.get_weight("f", "wmat")
    bias = tr.get_weight("f", "bias")
    np.testing.assert_allclose(pred, (x @ w.T + bias)[:, 0], rtol=1e-4)


def test_extract_feature_by_name_and_top():
    tr = make_trainer()
    x, y = toy_data(16)
    b = DataBatch(data=x[:16], label=y[:16])
    f1 = tr.extract_feature(b, "fc1")
    assert f1.shape == (16, 32)
    # top[-1] = last node (softmax output)
    fo = tr.extract_feature(b, "top[-1]")
    assert fo.shape == (16, 4)
    np.testing.assert_allclose(fo.sum(-1), 1.0, rtol=1e-4)


def test_training_with_extra_data():
    """Side inputs (extra_data_num) must flow through the TRAIN path too."""
    cfg = """
extra_data_num = 1
extra_data_shape[0] = 1,1,3
netconfig=start
layer[0->2] = fullc:f1
  nhidden = 5
layer[in_1->3] = fullc:f2
  nhidden = 5
layer[2,3->4] = concat
layer[4->5] = fullc:f3
  nhidden = 2
layer[+0] = softmax
netconfig=end
input_shape = 1,1,4
batch_size = 8
eta = 0.1
"""
    from cxxnet_tpu.io.data import DataBatch

    tr = NetTrainer()
    tr.set_params(C.parse_pairs(cfg))
    tr.init_model()
    rng = np.random.RandomState(0)
    b = DataBatch(
        data=rng.randn(8, 4).astype(np.float32),
        label=np.zeros((8, 1), np.float32),
        extra_data=[rng.randn(8, 3).astype(np.float32)],
    )
    tr.update(b)  # must not raise
    assert tr.epoch_counter == 1
    out = tr.predict(b)
    assert out.shape == (8,)


def test_bfloat16_mixed_precision_converges():
    """compute_dtype=bfloat16: bf16 layer math, f32 master params + loss."""
    import jax.numpy as jnp

    tr = make_trainer("compute_dtype = bfloat16\n")
    assert tr.net.compute_dtype == jnp.bfloat16
    x, y = toy_data()
    for _ in range(60):
        for b in batches(x, y):
            tr.update(b)
    # master params stay f32
    for leaf in __import__("jax").tree_util.tree_leaves(tr.params):
        assert leaf.dtype == jnp.float32
    errs = []
    for b in batches(x, y):
        pred = tr.predict(b)
        errs.append((pred != b.label[:, 0]).mean())
    assert float(np.mean(errs)) <= 0.1


def test_remat_trains_identically():
    """remat=1 recomputes activations in backprop; numerics unchanged."""
    t_plain = make_trainer()
    t_remat = make_trainer("remat = 1\n")
    assert t_remat.net.remat == 1
    x, y = toy_data(32)
    for tr in (t_plain, t_remat):
        for b in batches(x, y):
            tr.update(b)
    for key in t_plain.params:
        for tag in t_plain.params[key]:
            np.testing.assert_allclose(
                np.asarray(t_plain.params[key][tag]),
                np.asarray(t_remat.params[key][tag]),
                rtol=1e-5, atol=1e-6,
            )


def test_batchnorm_running_stats():
    """bn_eval=running: eval uses EMA statistics carried as aux state and
    checkpointed; default stays reference batch-stats parity."""
    cfg = """
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 16
  init_sigma = 0.1
layer[+0] = batch_norm:bn1
  bn_eval = running
  bn_momentum = 0.5
layer[+1:a1] = relu:a1
layer[a1->out] = fullc:fc2
  nhidden = 4
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,8
batch_size = 16
eta = 0.1
"""
    tr = NetTrainer()
    tr.set_params(C.parse_pairs(cfg))
    tr.init_model()
    key = [k for k in tr.aux if "bn1" in k][0]
    assert np.all(np.asarray(tr.aux[key]["rmean"]) == 0)
    x, y = toy_data(32)
    for b in batches(x, y):
        tr.update(b)
    rmean = np.asarray(tr.aux[key]["rmean"])
    assert np.abs(rmean).max() > 0, "EMA stats did not update"
    # eval path consumes the running stats without error
    pred = tr.predict(DataBatch(data=x[:16], label=y[:16]))
    assert pred.shape == (16,)
    # aux round-trips through checkpoints
    import tempfile, os
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "m.model")
        tr.save_model(path)
        tr2 = NetTrainer()
        tr2.set_params(C.parse_pairs(cfg))
        tr2.load_model(path)
        np.testing.assert_allclose(
            np.asarray(tr2.aux[key]["rmean"]), rmean)
    # default (no bn_eval): no aux state, reference parity
    tr3 = NetTrainer()
    tr3.set_params(C.parse_pairs(cfg.replace("  bn_eval = running\n", "")))
    tr3.init_model()
    assert tr3.aux == {}


def test_remat_with_running_stats():
    """remat=1 + bn_eval=running: stateful layers are checkpointed too
    (state outputs are non-differentiable); numerics match no-remat."""
    cfg = """
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 16
  init_sigma = 0.1
layer[+0] = batch_norm:bn1
  bn_eval = running
  bn_momentum = 0.5
layer[+1:a1] = relu:a1
layer[a1->out] = fullc:fc2
  nhidden = 4
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,8
batch_size = 16
eta = 0.1
"""
    x, y = toy_data(32)
    trainers = []
    for extra in ("", "remat = 1\n"):
        tr = NetTrainer()
        tr.set_params(C.parse_pairs(cfg + extra))
        tr.init_model()
        if extra:
            assert tr.net.remat == 1
        for b in batches(x, y):
            tr.update(b)
        trainers.append(tr)
    t_plain, t_remat = trainers
    key = [k for k in t_plain.aux if "bn1" in k][0]
    np.testing.assert_allclose(
        np.asarray(t_plain.aux[key]["rmean"]),
        np.asarray(t_remat.aux[key]["rmean"]), rtol=1e-5, atol=1e-6)
    for k in t_plain.params:
        for tag in t_plain.params[k]:
            np.testing.assert_allclose(
                np.asarray(t_plain.params[k][tag]),
                np.asarray(t_remat.params[k][tag]),
                rtol=1e-5, atol=1e-6, err_msg=f"{k}/{tag}")


def test_short_final_train_batch_pad_and_mask():
    """A short train batch is zero-padded to the compiled batch size with
    padded rows masked out of the loss (the static-shape AdjustBatchSize,
    neural_net-inl.hpp:266-277): gradient comes from real rows only."""
    x, y = toy_data(10)
    tr_b = make_trainer()  # batch_size = 16
    tr_b.update(DataBatch(data=x, label=y))  # 10-row short batch
    assert tr_b.epoch_counter == 1

    # ground truth: masked loss = sum(real-row losses) / 16, which a
    # batch_size=10 trainer reproduces with grad_scale = 10/16
    cfg = MLP_CFG.replace("batch_size = 16", "batch_size = 10").replace(
        "layer[+0] = softmax",
        "layer[+0] = softmax\n  grad_scale = 0.625",
    )
    tr_a = NetTrainer()
    tr_a.set_params(C.parse_pairs(cfg))
    tr_a.init_model()
    tr_a.update(DataBatch(data=x, label=y))

    for key in tr_a.params:
        for tag in tr_a.params[key]:
            np.testing.assert_allclose(
                np.asarray(tr_a.params[key][tag]),
                np.asarray(tr_b.params[key][tag]),
                rtol=1e-5, atol=1e-6, err_msg=f"{key}/{tag}")

    # an oversize batch is a clear error, not silent truncation
    xb, yb = toy_data(20)
    with pytest.raises(ValueError, match="exceeds batch_size"):
        tr_b.update(DataBatch(data=xb, label=yb))


def test_num_batch_padd_rows_masked_in_training():
    """The IO chain's full-size final batch carries num_batch_padd filler
    rows (round_batch=0); update() must zero their loss contribution."""
    x, y = toy_data(16)
    garbage = DataBatch(
        data=x, label=y, num_batch_padd=6
    )  # rows 10..15 are filler
    tr_b = make_trainer()
    tr_b.update(garbage)

    cfg = MLP_CFG.replace("batch_size = 16", "batch_size = 10").replace(
        "layer[+0] = softmax",
        "layer[+0] = softmax\n  grad_scale = 0.625",
    )
    tr_a = NetTrainer()
    tr_a.set_params(C.parse_pairs(cfg))
    tr_a.init_model()
    tr_a.update(DataBatch(data=x[:10], label=y[:10]))

    for key in tr_a.params:
        for tag in tr_a.params[key]:
            np.testing.assert_allclose(
                np.asarray(tr_a.params[key][tag]),
                np.asarray(tr_b.params[key][tag]),
                rtol=1e-5, atol=1e-6, err_msg=f"{key}/{tag}")


def test_update_scan_matches_sequential_updates():
    """update_scan (lax.scan over the fused step, ONE device program)
    must advance params/epoch exactly like K sequential update() calls.
    The scan path is how a TPU training loop amortizes per-dispatch host
    cost (doc/performance.md)."""
    K = 5
    rng = np.random.RandomState(3)
    data = rng.randn(K, 16, 8).astype(np.float32)
    w = rng.randn(8, 4).astype(np.float32)
    labels = (data @ w).argmax(-1).astype(np.float32)[..., None]

    tr_seq = make_trainer()
    for i in range(K):
        tr_seq.update(DataBatch(data=data[i], label=labels[i]))

    tr_scan = make_trainer()
    losses = tr_scan.update_scan(data, labels)
    assert losses.shape == (K,)
    assert tr_scan.epoch_counter == K == tr_seq.epoch_counter
    for key in tr_seq.params:
        for tag in tr_seq.params[key]:
            np.testing.assert_allclose(
                np.asarray(tr_seq.params[key][tag]),
                np.asarray(tr_scan.params[key][tag]),
                rtol=1e-5, atol=1e-5, err_msg=f"{key}/{tag}")
    # train metrics were accumulated for all K steps
    line = tr_scan.train_metric.print("train")
    assert "train-error" in line


def test_update_scan_single_batch_mode():
    """[B,...] + n_steps: the same staged batch is reused each step
    (synthetic benchmark mode); loss must strictly decrease."""
    x, y = toy_data(16)
    tr = make_trainer()
    tr.eval_train = 0
    losses = tr.update_scan(x, y, n_steps=6)
    assert losses.shape == (6,)
    assert tr.epoch_counter == 6
    assert losses[-1] < losses[0], losses


def test_update_scan_requires_update_period_1():
    tr = make_trainer(extra="update_period = 2\n")
    x, y = toy_data(16)
    with pytest.raises(ValueError, match="update_period"):
        tr.update_scan(x, y, n_steps=2)


def test_save_ustate_exact_resume(tmp_path):
    """save_ustate=1 checkpoints momentum; load restores it bit-exact,
    so a resumed run continues identically. Default keeps the reference
    quirk (momentum NOT saved, restarts from zero)."""
    # dropout included: exact resume must continue the SAME rng stream
    # (the checkpoint carries the key), not just optimizer state
    cfg = [
        ("dev", "cpu"), ("batch_size", "8"), ("input_shape", "1,1,6"),
        ("eta", "0.1"), ("momentum", "0.9"),
        ("netconfig", "start"),
        ("layer[0->1]", "fullc:fc"), ("nhidden", "4"),
        ("layer[1->1]", "dropout"), ("threshold", "0.3"),
        ("layer[1->1]", "softmax"),
        ("netconfig", "end"),
    ]
    rng = np.random.RandomState(0)
    data = rng.randn(6, 8, 6).astype(np.float32)
    labels = rng.randint(0, 4, (6, 8, 1)).astype(np.float32)

    def train(tr, lo, hi):
        for i in range(lo, hi):
            tr.update_all(data[i], labels[i])

    # continuous run = ground truth
    t_full = NetTrainer(); t_full.set_params(cfg); t_full.init_model()
    train(t_full, 0, 6)

    # save at step 3 WITH ustate, resume, finish
    t_a = NetTrainer(); t_a.set_params(cfg)
    t_a.set_param("save_ustate", "1")
    t_a.init_model()
    train(t_a, 0, 3)
    ck = str(tmp_path / "m.model")
    t_a.save_model(ck)
    t_b = NetTrainer(); t_b.set_params(cfg)
    t_b.load_model(ck)
    st = t_b.ustates["l0_fc"]["wmat"]
    assert float(np.abs(np.asarray(st["m"])).max()) > 0  # momentum restored
    train(t_b, 3, 6)
    for tag in t_full.params["l0_fc"]:
        np.testing.assert_allclose(
            np.asarray(t_b.params["l0_fc"][tag]),
            np.asarray(t_full.params["l0_fc"][tag]),
            rtol=1e-5, atol=1e-6,
            err_msg=f"exact resume diverged on {tag}",
        )

    # default: momentum NOT saved (reference parity)
    t_c = NetTrainer(); t_c.set_params(cfg); t_c.init_model()
    train(t_c, 0, 3)
    ck2 = str(tmp_path / "m2.model")
    t_c.save_model(ck2)
    t_d = NetTrainer(); t_d.set_params(cfg)
    t_d.load_model(ck2)
    st = t_d.ustates["l0_fc"]["wmat"]
    assert float(np.abs(np.asarray(st["m"])).max()) == 0


MIDNODE_CFG = """
netconfig=start
layer[0->hid] = fullc:f1
  nhidden = 4
  init_sigma = 0.3
layer[hid->out] = fullc:f2
  nhidden = 4
  init_sigma = 0.3
layer[out->out] = softmax
netconfig=end
input_shape = 1,1,8
batch_size = 16
eta = 0.1
metric = error
metric[label,hid] = error
"""


def test_metric_node_selection_eval():
    """metric[field,node] scores the named mid-graph node
    (nnet_impl-inl.hpp:57-67, 363-372) — not just the final out."""
    tr = NetTrainer()
    tr.set_params(C.parse_pairs(MIDNODE_CFG))
    tr.init_model()
    assert tr.metric.nodes == [None, "hid"]
    x, y = toy_data(32)

    class OneShot:
        def __init__(self):
            self.done = False

        def before_first(self):
            self.done = False

        def next(self):
            if self.done:
                return False
            self.done = True
            return True

        def value(self):
            return DataBatch(data=x[:16], label=y[:16])

    line = tr.evaluate(OneShot(), "val")
    assert line.count("val-error") == 2
    # the node-bound metric must equal argmax over the hid node's values
    hid = tr.extract_feature(DataBatch(data=x[:16], label=y[:16]), "hid")
    expect = float((hid.argmax(1) != y[:16, 0]).mean())
    assert abs(tr.metric.metrics[1].get() - expect) < 1e-6
    # and differ from the final-out metric in general
    out_err = tr.metric.metrics[0].get()
    assert tr.metric.metrics[1].cnt_inst == 16
    assert isinstance(out_err, float)


def test_metric_node_selection_train():
    """eval_train with a node-bound metric runs the extra node forward."""
    tr = NetTrainer()
    tr.set_params(C.parse_pairs(MIDNODE_CFG + "eval_train = 1\n"))
    tr.init_model()
    x, y = toy_data(16)
    tr.update(DataBatch(data=x, label=y))
    assert tr.train_metric.metrics[1].cnt_inst == 16
    line = tr.evaluate(None, "train")
    assert line.count("train-error") == 2


def test_metric_bad_node_fails_at_init():
    tr = NetTrainer()
    tr.set_params(C.parse_pairs(
        MIDNODE_CFG.replace("metric[label,hid]", "metric[label,hdi]")
    ))
    with pytest.raises(ValueError, match="hdi"):
        tr.init_model()


def test_metric_node_same_weights_as_base():
    """Node-bound and final-out train metrics must score the SAME
    (pre-update) weight version in the fused update_period=1 path."""
    cfg = MIDNODE_CFG.replace("metric[label,hid] = error",
                              "metric[label,out] = error")
    tr = NetTrainer()
    tr.set_params(C.parse_pairs(cfg + "eval_train = 1\n"))
    tr.init_model()
    x, y = toy_data(16)
    tr.update(DataBatch(data=x, label=y))
    # 'out' IS the final node: both metrics see identical predictions,
    # so identical error — any pre/post-update skew would break this
    assert tr.train_metric.metrics[0].get() == tr.train_metric.metrics[1].get()


def test_update_scan_rejects_node_metrics():
    tr = NetTrainer()
    tr.set_params(C.parse_pairs(MIDNODE_CFG + "eval_train = 1\n"))
    tr.init_model()
    x, y = toy_data(16)
    with pytest.raises(ValueError, match="node-bound"):
        tr.update_scan(x, y, n_steps=2)
    # with eval_train off the scan path is allowed again
    tr2 = NetTrainer()
    tr2.set_params(C.parse_pairs(MIDNODE_CFG + "eval_train = 0\n"))
    tr2.init_model()
    tr2.update_scan(x, y, n_steps=2)
    assert tr2.epoch_counter == 2


INCEPTION_CFG = """
netconfig=start
layer[0->stem] = conv:stem
  kernel_size = 3
  pad = 1
  nchannel = 8
  init_sigma = 0.1
layer[stem->stem] = relu
layer[stem->b1] = conv:br1
  kernel_size = 1
  nchannel = 4
  init_sigma = 0.1
layer[stem->b2] = conv:br2
  kernel_size = 1
  nchannel = 6
  init_sigma = 0.1
layer[stem->b3] = conv:br3
  kernel_size = 1
  nchannel = 2
  init_sigma = 0.1
layer[b1,b2,b3->cat] = ch_concat
layer[cat->fl] = flatten
layer[fl->out] = fullc:fc
  nhidden = 4
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 3,6,6
batch_size = 16
eta = 0.1
momentum = 0.9
metric = error
"""


@pytest.mark.parametrize("remat", ["0", "1"])
def test_fuse_1x1_sibling_convs_parity(remat):
    """fuse_1x1=1 executes the three sibling 1x1 branch convs as one
    concatenated conv; weights after training and predictions must match
    the unfused graph (same seed) to fp tolerance."""
    rng = np.random.RandomState(5)
    x = rng.randn(32, 6, 6, 3).astype(np.float32)
    y = rng.randint(0, 4, (32, 1)).astype(np.float32)

    def run(fuse):
        tr = NetTrainer()
        tr.set_params(C.parse_pairs(
            INCEPTION_CFG + f"fuse_1x1 = {fuse}\nremat = {remat}\n"
        ))
        tr.set_param("seed", "7")
        tr.init_model()
        groups, member = tr.net._sibling_1x1_groups()
        if fuse:
            assert [len(v) for v in groups.values()] == [3]
        for _ in range(3):
            for b in batches(x, y):
                tr.update(b)
        preds = np.concatenate(
            [tr.predict(b) for b in batches(x, y)]
        )
        return preds, jax.tree_util.tree_map(np.asarray, tr.params)

    p0, w0 = run(0)
    p1, w1 = run(1)
    f0 = {jax.tree_util.keystr(k): a
          for k, a in jax.tree_util.tree_leaves_with_path(w0)}
    f1 = {jax.tree_util.keystr(k): a
          for k, a in jax.tree_util.tree_leaves_with_path(w1)}
    assert sorted(f0) == sorted(f1)
    for k in f0:
        np.testing.assert_allclose(f0[k], f1[k], rtol=1e-5, atol=1e-5,
                                   err_msg=k)
    np.testing.assert_allclose(p0, p1, rtol=1e-5, atol=1e-5)


RESNET_BOUNDARY_CFG = """
netconfig=start
layer[0->stem] = conv:stem
  kernel_size = 3
  pad = 1
  nchannel = 8
  init_sigma = 0.1
layer[stem->stem] = relu
layer[stem->a] = conv:reduce
  kernel_size = 1
  stride = 2
  nchannel = 4
  init_sigma = 0.1
layer[a->ar] = relu
layer[ar->b] = conv:mid
  kernel_size = 3
  pad = 1
  nchannel = 4
  init_sigma = 0.1
layer[b->c] = conv:expand
  kernel_size = 1
  nchannel = 6
  init_sigma = 0.1
layer[stem->p] = conv:proj
  kernel_size = 1
  stride = 2
  nchannel = 6
  init_sigma = 0.1
layer[p,c->sum] = eltwise_sum
layer[sum->sum] = relu
layer[sum->fl] = flatten
layer[fl->out] = fullc:fc
  nhidden = 4
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 3,6,6
batch_size = 16
eta = 0.1
momentum = 0.9
metric = error
"""


def test_fuse_1x1_strided_sibling_pair_parity():
    """Stride-2 1x1 siblings reading one node (ResNet's stage-boundary
    reduce + projection convs) fuse into one strided conv; the stride-1
    expand conv must NOT join their group (different key).  Training +
    prediction parity vs the unfused graph."""
    rng = np.random.RandomState(6)
    x = rng.randn(32, 6, 6, 3).astype(np.float32)
    y = rng.randint(0, 4, (32, 1)).astype(np.float32)

    def run(fuse):
        tr = NetTrainer()
        tr.set_params(C.parse_pairs(
            RESNET_BOUNDARY_CFG + f"fuse_1x1 = {fuse}\n"
        ))
        tr.set_param("seed", "9")
        tr.init_model()
        groups, _ = tr.net._sibling_1x1_groups()
        if fuse:
            # exactly one group: the two s2 convs (reduce + proj)
            assert [len(v) for v in groups.values()] == [2]
            (idxs,) = groups.values()
            names = {tr.net.graph.layers[j].name for j in idxs}
            assert names == {"reduce", "proj"}
        for _ in range(3):
            for b in batches(x, y):
                tr.update(b)
        preds = np.concatenate([tr.predict(b) for b in batches(x, y)])
        return preds, jax.tree_util.tree_map(np.asarray, tr.params)

    p0, w0 = run(0)
    p1, w1 = run(1)
    for k, (a, b) in {
        k: (a, b)
        for (k, a), (_, b) in zip(
            sorted((jax.tree_util.keystr(kp), leaf)
                   for kp, leaf in jax.tree_util.tree_leaves_with_path(w0)),
            sorted((jax.tree_util.keystr(kp), leaf)
                   for kp, leaf in jax.tree_util.tree_leaves_with_path(w1)),
        )
    }.items():
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5, err_msg=k)
    np.testing.assert_allclose(p0, p1, rtol=1e-5, atol=1e-5)


def test_fuse_1x1_respects_selfloop_writes():
    """A self-loop layer (relu writing the shared node) between sibling
    1x1 declarations versions the node: siblings across the write must
    NOT fuse (they read different values)."""
    cfg = """
netconfig=start
layer[0->stem] = conv:stem
  kernel_size = 3
  pad = 1
  nchannel = 8
  init_sigma = 0.1
layer[stem->b1] = conv:br1
  kernel_size = 1
  nchannel = 4
  init_sigma = 0.1
layer[stem->stem] = relu
layer[stem->b2] = conv:br2
  kernel_size = 1
  nchannel = 4
  init_sigma = 0.1
layer[b1,b2->cat] = ch_concat
layer[cat->fl] = flatten
layer[fl->out] = fullc:fc
  nhidden = 4
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 3,6,6
batch_size = 8
eta = 0.1
metric = error
fuse_1x1 = 1
"""
    tr = NetTrainer()
    tr.set_params(C.parse_pairs(cfg))
    tr.init_model()
    groups, member = tr.net._sibling_1x1_groups()
    assert groups == {} and member == {}  # the relu write splits them

    # and the net still trains correctly through the plain path
    rng = np.random.RandomState(2)
    x = rng.randn(8, 6, 6, 3).astype(np.float32)
    y = rng.randint(0, 4, (8, 1)).astype(np.float32)
    tr.update(DataBatch(data=x, label=y))
    tr.sync()
