"""Observability subsystem tests: registry, spans, events, facades.

Covers the obs/ primitives (metrics registry + Prometheus exposition,
span tracing, event log) and the satellite fixes that rode along with
them: window-consistent PercentileTracker summaries, swap-atomic
PipelineStats.reset, and the queue-depth error counter replacing the
``-1`` sentinel.  The exposition text is validated with the SAME parser
``tools/obs_dump.py --check`` uses in the OBS=1 CI lane, so the test
and the lane can never disagree about what "valid" means.
"""

import json
import os
import sys
import threading
import time

import pytest

from cxxnet_tpu.obs.events import EventLog
from cxxnet_tpu.obs.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    escape_label_value,
    registry,
)
from cxxnet_tpu.obs.trace import Tracer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import obs_dump  # noqa: E402 - the CI lane's validator, under test too


# ----------------------------------------------------------------------
# PercentileTracker (the facade over obs.PercentileWindow)
def test_tracker_empty_window():
    from cxxnet_tpu.utils.profiler import PercentileTracker

    t = PercentileTracker(window=8)
    assert t.summary() == {"count": 0}
    assert t.percentiles() == {}
    assert t.count == 0


def test_tracker_window_one():
    from cxxnet_tpu.utils.profiler import PercentileTracker

    t = PercentileTracker(window=1)
    for v in (10.0, 20.0, 30.0):
        t.add(v)
    s = t.summary()
    # the window is exactly the newest sample; lifetime covers all three
    assert s["count"] == 3
    assert s["mean"] == 30.0 == s["p50"] == s["p95"] == s["p99"]
    assert s["lifetime_mean"] == pytest.approx(20.0)


def test_tracker_exact_ring_wraparound():
    from cxxnet_tpu.utils.profiler import PercentileTracker

    t = PercentileTracker(window=4)
    for v in (1.0, 2.0, 3.0, 4.0):  # fills the ring exactly
        t.add(v)
    assert t.summary()["mean"] == pytest.approx(2.5)
    for v in (10.0, 20.0, 30.0, 40.0):  # overwrites every slot once
        t.add(v)
    s = t.summary()
    assert s["count"] == 8
    # window == the second batch only; mean is window-consistent with
    # the percentiles (the old code reported the lifetime mean here)
    assert s["mean"] == pytest.approx(25.0)
    assert s["lifetime_mean"] == pytest.approx(110.0 / 8)
    assert s["p50"] == 20.0 and s["p99"] == 40.0


def test_tracker_summary_scale_applies_to_all_values():
    from cxxnet_tpu.utils.profiler import PercentileTracker

    t = PercentileTracker(window=4)
    t.add(0.5)
    s = t.summary(scale=1e3)
    assert s["mean"] == s["lifetime_mean"] == s["p50"] == 500.0


# ----------------------------------------------------------------------
# metrics registry
def test_counter_gauge_histogram_basics():
    reg = MetricsRegistry()
    c = reg.counter("req_total", "requests", labelnames=("outcome",))
    c.labels(outcome="ok").inc()
    c.labels(outcome="ok").inc(2)
    c.labels(outcome="shed").inc()
    assert c.labels(outcome="ok").value == 3
    with pytest.raises(ValueError):
        c.labels(outcome="ok").inc(-1)  # counters only go up
    g = reg.gauge("depth", "queue depth")
    g.set(5)
    g.dec()
    assert g.get() == 4
    h = reg.histogram("lat_seconds", "latency", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    (name, labels, acc1), (_, _, acc2), (_, _, inf), (_, _, total), \
        (_, _, count) = h.samples()
    assert name == "lat_seconds_bucket" and 'le="0.1"' in labels
    assert (acc1, acc2, inf) == (1, 2, 3)  # cumulative
    assert total == pytest.approx(5.55) and count == 3


def test_registry_get_or_create_and_conflicts():
    reg = MetricsRegistry()
    a = reg.counter("x_total", "x")
    assert reg.counter("x_total") is a  # shared, not forked
    with pytest.raises(ValueError):
        reg.gauge("x_total")  # same name, different kind
    with pytest.raises(ValueError):
        reg.counter("x_total", labelnames=("k",))  # different labels
    with pytest.raises(ValueError):
        reg.counter("0bad")  # invalid metric name
    with pytest.raises(ValueError):
        reg.counter("ok_total", labelnames=("0bad",))
    h = reg.histogram("h", buckets=(1, 2))
    assert reg.histogram("h", buckets=(1, 2)) is h
    with pytest.raises(ValueError):
        reg.histogram("h", buckets=(1, 2, 3))


def test_label_escaping_and_exposition_validity():
    reg = MetricsRegistry()
    c = reg.counter("esc_total", 'tricky "help"\nwith newline',
                    labelnames=("path",))
    nasty = 'a\\b"c\nd'
    c.labels(path=nasty).inc()
    text = reg.render_prometheus()
    assert '\\\\b\\"c\\nd' in text  # escaped, single line
    assert text.count("\n# ") <= text.count("# ")  # still line-structured
    problems = obs_dump.validate_prometheus_text(text)
    assert problems == [], problems
    # the escaped value round-trips through the lane's parser
    line = [l for l in text.splitlines() if l.startswith("esc_total{")][0]
    labels = obs_dump._parse_labels(line[len("esc_total"):line.rindex(" ")])
    assert labels == {"path": nasty}
    assert escape_label_value("plain") == "plain"


def test_full_registry_exposition_is_valid():
    reg = MetricsRegistry()
    reg.counter("a_total", "a").inc()
    reg.gauge("b", "b").set(-1.5)
    reg.histogram("c_seconds", "c", labelnames=("op",),
                  buckets=(0.01, 0.1)).labels(op="x").observe(0.05)

    def collector():
        return [("d_rows_total", "counter", "collected",
                 [({"stage": "decode"}, 7)])]

    reg.register_collector(collector)
    text = reg.render_prometheus()
    assert 'd_rows_total{stage="decode"} 7' in text
    problems = obs_dump.validate_prometheus_text(text)
    assert problems == [], problems


def test_gauge_function_failure_yields_absent_sample():
    reg = MetricsRegistry()
    g = reg.gauge("live", "live gauge")
    g.set_function(lambda: 1 / 0)
    text = reg.render_prometheus()
    assert "# TYPE live gauge" in text
    assert "\nlive " not in text  # sample absent, not a sentinel
    assert obs_dump.validate_prometheus_text(text) == []


def test_exposition_validator_catches_breakage():
    bad = "# TYPE h histogram\nh_bucket{le=\"1\"} 5\nh_bucket{le=\"+Inf\"} 3\n"
    probs = obs_dump.validate_prometheus_text(bad)
    assert any("cumulative" in p for p in probs)
    assert any("_sum/_count" in p for p in probs)
    assert obs_dump.validate_prometheus_text("x{bad} 1\n")
    assert obs_dump.validate_prometheus_text("x 1 2 3 4\n")


# ----------------------------------------------------------------------
# span tracing
def test_span_nesting_and_parent_tracking():
    t = Tracer()
    t.enable()
    with t.span("outer", round=3) as outer:
        with t.span("inner"):
            pass
        outer.set(rows=5)
    spans = {s.name: s for s in t.spans()}
    assert spans["inner"].parent_id == spans["outer"].span_id
    assert spans["outer"].parent_id is None
    assert spans["outer"].args == {"round": 3, "rows": 5}
    assert spans["inner"].dur_us <= spans["outer"].dur_us


def test_span_nesting_across_threads():
    """Parent tracking is thread-local: a span opened on a worker thread
    must not parent under the main thread's open span, and each span
    carries its own thread id for the trace viewer."""
    t = Tracer()
    t.enable()
    done = threading.Event()

    def worker():
        with t.span("worker_span"):
            pass
        done.set()

    with t.span("main_span"):
        th = threading.Thread(target=worker)
        th.start()
        th.join()
    assert done.wait(5)
    spans = {s.name: s for s in t.spans()}
    assert spans["worker_span"].parent_id is None
    assert spans["worker_span"].tid != spans["main_span"].tid
    doc = t.to_chrome_trace()
    names = {e["name"] for e in doc["traceEvents"]}
    assert {"main_span", "worker_span", "thread_name"} <= names
    for e in doc["traceEvents"]:
        if e["name"] == "thread_name":
            continue
        assert e["ph"] == "X" and e["dur"] >= 0


def test_span_ring_is_bounded_and_disabled_is_noop():
    t = Tracer(ring=4)
    t.enable()
    for i in range(10):
        with t.span(f"s{i}"):
            pass
    assert len(t.spans()) == 4
    assert t.dropped == 6
    assert [s.name for s in t.spans()] == ["s6", "s7", "s8", "s9"]
    t2 = Tracer()  # disabled: shared no-op, nothing recorded
    with t2.span("never") as s:
        s.set(ignored=1)
    assert t2.spans() == []


def test_trace_export_and_step_window(tmp_path):
    t = Tracer()
    t.configure([("trace_dir", str(tmp_path)), ("trace_steps", "2")])
    assert t.enabled
    with t.span("step_work"):
        pass
    t.step(0)
    assert os.listdir(tmp_path) == []  # window still open
    t.step(1)
    files = os.listdir(tmp_path)
    assert len(files) == 1 and files[0].endswith(".json")
    doc = json.load(open(tmp_path / files[0]))
    assert any(e["name"] == "step_work" for e in doc["traceEvents"])
    assert not t.enabled  # one-window discipline
    t.step(2)  # idempotent after the flush
    assert len(os.listdir(tmp_path)) == 1


# ----------------------------------------------------------------------
# event log
def test_event_log_ring_and_reserved_fields():
    log = EventLog(ring=3)
    log.emit("a.b", x=1)
    rec = log.emit("c.d", kind="field-kind", ts=123)
    assert rec["kind"] == "c.d"  # the envelope wins
    assert rec["kind_"] == "field-kind" and rec["ts_"] == 123
    for i in range(5):
        log.emit("spam", i=i)
    assert len(log.recent(50)) == 3  # bounded ring
    assert log.recent(50, kind="a.b") == []  # aged out


def test_event_log_rotation(tmp_path):
    log = EventLog()
    path = str(tmp_path / "events.jsonl")
    log.configure([("event_log", path),
                   ("event_log_max_bytes", "2048"),
                   ("event_log_backups", "2")])
    for i in range(300):
        log.emit("rot.test", i=i, pad="x" * 30)
    names = sorted(os.listdir(tmp_path))
    assert names == ["events.jsonl", "events.jsonl.1", "events.jsonl.2"]
    for name in names:
        assert os.path.getsize(tmp_path / name) <= 2048 + 256
        for line in open(tmp_path / name, encoding="utf-8"):
            assert json.loads(line)["kind"] == "rot.test"
    assert log.dropped == 0
    # the validator the CI lane runs accepts what rotation produced
    assert obs_dump.validate_events(path) == []


def test_event_log_never_raises(tmp_path):
    log = EventLog()
    # a path component beyond NAME_MAX: makedirs/open fail with OSError
    log.configure([("event_log", str(tmp_path / ("n" * 300) / "x.jsonl"))])
    log.emit("unwritable", data=object())  # coerced, swallowed
    assert log.dropped >= 0  # no exception is the assertion
    assert log.recent(1)[0]["kind"] == "unwritable"


def test_emit_once_dedupes_recurring_facts():
    log = EventLog()
    assert log.emit_once("ck:/m/0007.model:crc", "checkpoint.skipped",
                         path="/m/0007.model")
    for _ in range(5):  # the reload poll hitting the same bad checkpoint
        assert not log.emit_once("ck:/m/0007.model:crc",
                                 "checkpoint.skipped", path="/m/0007.model")
    assert len(log.recent(50, kind="checkpoint.skipped")) == 1
    assert log.suppressed_count("ck:/m/0007.model:crc") == 6


def test_failed_flush_still_disables_tracing(tmp_path):
    t = Tracer()
    blocker = tmp_path / "not_a_dir"
    blocker.write_text("file where trace_dir should be")
    t.configure([("trace_dir", str(blocker / "sub")), ("trace_steps", "1")])
    with t.span("s"):
        pass
    t.step(0)  # export fails (parent is a file) — must not raise
    assert not t.enabled  # ...and must not keep paying span cost


def test_registry_snapshot_includes_collectors():
    reg = MetricsRegistry()
    reg.counter("direct_total").inc(2)
    reg.register_collector(lambda: [
        ("collected", "gauge", "", [({"stage": "x"}, 1.5)]),
    ])
    snap = reg.snapshot()
    assert snap["direct_total"] == {"direct_total": 2.0}
    assert snap["collected"] == {'collected{stage="x"}': 1.5}


def test_log_exception_once_dedupes():
    log = EventLog()
    assert log.log_exception_once("site", ValueError("boom"), kind="err")
    assert not log.log_exception_once("site", ValueError("boom"), kind="err")
    assert log.suppressed_count("site") == 2
    assert len(log.recent(50, kind="err")) == 1
    rec = log.recent(50, kind="err")[0]
    assert "boom" in rec["error"] and rec["deduped"] is True


# ----------------------------------------------------------------------
# facades: PipelineStats atomicity, queue-depth errors
def test_pipeline_stats_reset_is_swap_atomic():
    """Concurrent add() during reset(): every sample lands wholly in one
    epoch — the snapshot's count and the tracker's count can never
    disagree (the old code could add to a discarded tracker)."""
    from cxxnet_tpu.utils.profiler import PipelineStats

    ps = PipelineStats(window=64)
    stop = threading.Event()
    errors = []

    def adder():
        try:
            while not stop.is_set():
                ps.add("decode", 0.001, rows=2)
        except BaseException as e:  # noqa: BLE001 - must fail the test
            errors.append(e)

    def resetter():
        for _ in range(200):
            ps.reset()

    threads = [threading.Thread(target=adder) for _ in range(4)]
    for th in threads:
        th.start()
    try:
        resetter()
    finally:
        stop.set()
        for th in threads:
            th.join(5)
    snap = ps.snapshot()["decode"]
    # rows are recorded 2-per-add atomically with the count
    assert snap["rows"] == 2 * snap["count"]
    if snap["count"]:
        assert "mean_ms" in snap and "lifetime_mean_ms" in snap
    assert not errors


def test_serving_stats_queue_depth_error_counter():
    from cxxnet_tpu.serve.metrics import ServingStats

    s = ServingStats()
    s.bind_queue_depth(lambda: 7)
    snap = s.snapshot()
    assert snap["queue_depth"] == 7 and snap["queue_depth_errors"] == 0

    def broken():
        raise RuntimeError("gauge wiring broke")

    s.bind_queue_depth(broken)
    snap = s.snapshot()
    assert "queue_depth" not in snap  # no -1 sentinel
    assert snap["queue_depth_errors"] == 1
    s.snapshot()
    assert s.snapshot()["queue_depth_errors"] == 3
    # the failure was event-logged once, not per scrape
    from cxxnet_tpu.obs import event_log

    recs = event_log().recent(50, kind="serve.gauge_error")
    assert len(recs) == 1 and "gauge wiring broke" in recs[0]["error"]


def test_serving_stats_feeds_shared_registry():
    from cxxnet_tpu.obs import registry
    from cxxnet_tpu.serve.metrics import ServingStats

    s = ServingStats()
    before = registry().counter(
        "serve_request_outcomes_total", labelnames=("outcome",)
    ).labels(outcome="ok").value
    s.record_request(4)
    s.record_outcome("ok", latency_s=0.005)
    after = registry().counter(
        "serve_request_outcomes_total", labelnames=("outcome",)
    ).labels(outcome="ok").value
    assert after == before + 1
    text = registry().render_prometheus()
    assert obs_dump.validate_prometheus_text(text) == [], "live registry"
    assert "serve_request_latency_seconds_bucket" in text


# ----------------------------------------------------------------------
# telemetry / event schema validators (the OBS=1 lane contract)
def test_validate_telemetry(tmp_path):
    good = {
        "ts": 1.0, "round": 0, "steps": 4, "eval": {"train-error": 0.5},
        "stages": {st: {"count": 0} for st in obs_dump.TELEMETRY_STAGES},
    }
    p = tmp_path / "telemetry.jsonl"
    with open(p, "w") as f:
        f.write(json.dumps(good) + "\n")
        f.write(json.dumps({**good, "round": 1}) + "\n")
    assert obs_dump.validate_telemetry(str(p)) == []
    with open(p, "a") as f:
        f.write(json.dumps({**good, "round": 0}) + "\n")  # backwards
    assert any("backwards" in x for x in obs_dump.validate_telemetry(str(p)))
    bad = dict(good)
    del bad["stages"]
    with open(p, "w") as f:
        f.write(json.dumps(bad) + "\n")
    assert obs_dump.validate_telemetry(str(p))
    assert obs_dump.validate_telemetry(str(tmp_path / "missing.jsonl"))


def test_validate_events_schema(tmp_path):
    p = tmp_path / "events.jsonl"
    with open(p, "w") as f:
        f.write(json.dumps({"ts": 1.0, "kind": "a"}) + "\n")
    assert obs_dump.validate_events(str(p)) == []
    with open(p, "a") as f:
        f.write(json.dumps({"ts": "notanumber", "kind": ""}) + "\n")
    probs = obs_dump.validate_events(str(p))
    assert any("ts" in x for x in probs) and any("kind" in x for x in probs)


# ----------------------------------------------------------------------
# concurrent scrapes (ISSUE 7 satellite): /metricsz + /alertz bodies
# rendered while worker threads hammer every pillar
def test_concurrent_scrapes_with_live_writers():
    """Concurrent exposition + alert-status reads while spans, events,
    counters and histograms are being recorded from worker threads: no
    torn exposition (every scrape parses clean), no deadlock, and the
    alert evaluator keeps evaluating throughout."""
    import json as _json

    from cxxnet_tpu.obs import alerts as obs_alerts
    from cxxnet_tpu.obs import device as obs_device
    from cxxnet_tpu.obs import emit, span, tracer

    sys.path.insert(0, os.path.join(REPO, "tools"))
    from obs_dump import validate_alertz, validate_prometheus_text

    tracer().enable(ring=256)
    reg = registry()
    c = reg.counter("t_scrape_total", "scrape test", labelnames=("k",))
    h = reg.histogram("t_scrape_seconds", "scrape test")
    obs_alerts.reset()
    ev = obs_alerts.evaluator()
    ev.configure([("alert", "t_scrape_busy:t_scrape_rate:>:1e12")])
    stop = threading.Event()
    errors = []

    def writer(i):
        k = f"w{i}"
        while not stop.is_set():
            try:
                c.labels(k=k).inc()
                h.observe(0.001 * i)
                with span("t.scrape", worker=i):
                    emit("t.scrape", worker=i)
            except Exception as e:  # noqa: BLE001 - collected below
                errors.append(e)
                return

    def scraper():
        while not stop.is_set():
            try:
                text = reg.render_prometheus()
                probs = validate_prometheus_text(text)
                if probs:
                    errors.append(AssertionError(probs[:3]))
                    return
                body = _json.loads(_json.dumps(ev.status()))
                probs = validate_alertz(body)
                if probs:
                    errors.append(AssertionError(probs[:3]))
                    return
                ev.evaluate_once()
            except Exception as e:  # noqa: BLE001 - collected below
                errors.append(e)
                return

    threads = ([threading.Thread(target=writer, args=(i,))
                for i in range(4)]
               + [threading.Thread(target=scraper) for _ in range(3)])
    for t in threads:
        t.start()
    time.sleep(1.0)
    stop.set()
    for t in threads:
        t.join(timeout=10)
        assert not t.is_alive(), "scrape/writer thread deadlocked"
    assert errors == []
    assert ev.evaluations > 0
    # the device-plane families render alongside without tearing either
    obs_device.device_metrics()
    assert validate_prometheus_text(reg.render_prometheus()) == []
    obs_alerts.reset()
    tracer().reset()
