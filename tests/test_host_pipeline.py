"""Parallel host data pipeline: determinism, vectorized-augment parity,
quarantine-through-the-pool, and the persistent compile cache.

The load-bearing contract (ISSUE 4): the augmentation stream is a pure
function of ``(seed_data, epoch, record index)`` — decode worker count,
chunking, buffer depth, and mid-epoch rewinds must produce
**bitwise-identical** batches to the serial path.
"""

import io as _io
import os

import numpy as np
import pytest

from cxxnet_tpu import config as cfgmod
from cxxnet_tpu.io.batch import DataInst, InstIterator
from cxxnet_tpu.io.data import create_iterator
from cxxnet_tpu.io.imgbin import BinPageWriter, encode_raw


def _write_jpeg_imgbin(tmp_path, n=23, size=16, page_size=4096):
    from PIL import Image

    rng = np.random.RandomState(0)
    binp = str(tmp_path / "d.bin")
    w = BinPageWriter(binp, page_size=page_size)
    lst = tmp_path / "d.lst"
    with open(lst, "w") as f:
        for i in range(n):
            img = (rng.rand(size, size, 3) * 255).astype(np.uint8)
            buf = _io.BytesIO()
            Image.fromarray(img).save(buf, "JPEG", quality=90)
            w.push(buf.getvalue())
            f.write(f"{i}\t{i % 3}\tx.jpg\n")
    w.close()
    return binp, str(lst)


AUG = """  rand_crop = 1
  rand_mirror = 1
  max_random_contrast = 0.2
  max_random_illumination = 5
  mean_value = 1,2,3
  scale = 0.0039
"""


def _chain(binp, lst, extra="", aug=AUG, batch=4, shape="3,12,12",
           round_batch=1):
    conf = f"""
data = train
iter = imgbin
  image_bin = "{binp}"
  image_list = "{lst}"
  native_decoder = 0
  silent = 1
{aug}  input_shape = {shape}
  batch_size = {batch}
  round_batch = {round_batch}
  label_width = 1
  seed_data = 7
{extra}
iter = end
"""
    sec = cfgmod.split_sections(cfgmod.parse_pairs(conf)).find("data")[0]
    it = create_iterator(sec.entries)
    it.init()
    return it


def _epochs(it, n_epochs=2):
    """Collect ``n_epochs`` of batches, then CLOSE the chain — every
    call site's last use of its iterator.  Leaving decode pools alive
    was this module's contribution to the suite-wide daemon-thread
    leak (the multi-file flake suspect conftest now bounds)."""
    out = []
    for _ in range(n_epochs):
        it.before_first()
        while it.next():
            b = it.value()
            out.append((b.data.tobytes(), b.label.tobytes(),
                        b.num_batch_padd))
    it.close()
    return out


@pytest.mark.parametrize("workers", [1, 4])
def test_pool_bitwise_identical_to_serial(tmp_path, workers):
    """num_decode_workers in {1, 4} == the serial path, bitwise, over
    two epochs (full augmentation armed: crop/mirror/mean/jitter/scale
    — the float tail runs split across worker and consumer)."""
    binp, lst = _write_jpeg_imgbin(tmp_path)
    ref = _epochs(_chain(binp, lst))
    got = _epochs(_chain(
        binp, lst,
        extra=f"  num_decode_workers = {workers}\n  decode_chunk = 3\n",
    ))
    assert got == ref


def test_pool_bitwise_identical_no_tail(tmp_path):
    """The tail-identity fast path (no mean/jitter/scale: uint8 flows
    to the batch store-cast) is also bitwise identical."""
    binp, lst = _write_jpeg_imgbin(tmp_path)
    aug = "  rand_crop = 1\n  rand_mirror = 1\n"
    ref = _epochs(_chain(binp, lst, aug=aug))
    got = _epochs(_chain(
        binp, lst, aug=aug,
        extra="  num_decode_workers = 4\n  decode_chunk = 3\n",
    ))
    assert got == ref


@pytest.mark.parametrize("workers", [0, 4])
def test_mid_epoch_rewind_restarts_the_stream(tmp_path, workers):
    """A before_first() mid-epoch starts the next epoch exactly where
    an uninterrupted run's next epoch would start: epoch 2 of run A ==
    the post-rewind pass of run B, serial and pooled alike."""
    binp, lst = _write_jpeg_imgbin(tmp_path)
    extra = (f"  num_decode_workers = {workers}\n  decode_chunk = 3\n"
             if workers else "")
    # round_batch=0: with round_batch=1 the tail wrap advances the
    # epoch mid-batch, so epochs are not self-contained units to align
    a = _chain(binp, lst, extra=extra, round_batch=0)
    full = _epochs(a, n_epochs=2)
    n_per_epoch = len(full) // 2
    epoch2 = full[n_per_epoch:]

    b = _chain(binp, lst, extra=extra, round_batch=0)
    b.before_first()
    for _ in range(2):  # half an epoch, then rewind
        assert b.next()
    got = _epochs(b, n_epochs=1)
    assert got == epoch2


def test_worker_count_changes_nothing_about_augment_draws(tmp_path):
    """Chunk geometry must not leak into the stream: odd chunk sizes
    and depths against each other."""
    binp, lst = _write_jpeg_imgbin(tmp_path)
    a = _epochs(_chain(
        binp, lst,
        extra="  num_decode_workers = 2\n  decode_chunk = 1\n"
              "  decode_queue_depth = 7\n",
    ))
    b = _epochs(_chain(
        binp, lst,
        extra="  num_decode_workers = 3\n  decode_chunk = 5\n"
              "  decode_queue_depth = 2\n",
    ))
    assert a == b


# ----------------------------------------------------------------------
# vectorized fast path == per-record path
class _ListSource(InstIterator):
    def __init__(self, insts):
        self.insts = insts
        self._pos = 0

    def before_first(self):
        self._pos = 0

    def next(self):
        if self._pos >= len(self.insts):
            return False
        self._pos += 1
        return True

    def value(self):
        return self.insts[self._pos - 1]


def _augmenter(params, meanimg=None):
    from cxxnet_tpu.io.augment import AugmentIterator

    aug = AugmentIterator(_ListSource([]))
    for k, v in params:
        aug.set_param(k, v)
    if meanimg is not None:
        aug._meanimg = meanimg
    return aug


def _rand_insts(rng, n=9, h=14, w=15, dtype=np.uint8):
    out = []
    for i in range(n):
        data = (rng.rand(h, w, 3) * 255).astype(dtype)
        out.append(DataInst(100 + i, data, np.asarray([i], np.float32)))
    return out


@pytest.mark.parametrize("mean", ["none", "value", "img_crop", "img_full"])
def test_augment_batch_matches_per_record(tmp_path, mean):
    rng = np.random.RandomState(3)
    params = [
        ("input_shape", "3,10,11"), ("rand_crop", "1"),
        ("rand_mirror", "1"), ("max_random_contrast", "0.3"),
        ("max_random_illumination", "8"), ("scale", "0.02"),
        ("seed_data", "11"),
    ]
    meanimg = None
    if mean == "value":
        params.append(("mean_value", "3,2,1"))
    elif mean == "img_crop":
        meanimg = (rng.rand(10, 11, 3) * 50).astype(np.float32)
    elif mean == "img_full":
        meanimg = (rng.rand(14, 15, 3) * 50).astype(np.float32)
    aug = _augmenter(params, meanimg)
    insts = _rand_insts(rng)
    vec = aug.augment_insts(insts, epoch=2)
    per = [
        aug._augmented(d, apply_mean=True, rng=aug.record_rng(2, d.index))
        for d in insts
    ]
    assert len(vec) == len(per)
    for v, p in zip(vec, per):
        assert v.data.dtype == p.data.dtype == np.float32
        assert v.data.tobytes() == p.data.tobytes()


def test_augment_pil_and_tail_match_per_record(tmp_path):
    """The split worker path (PIL crop/flip + consumer float tail) is
    bitwise-equal to the serial per-record augment."""
    from PIL import Image

    rng = np.random.RandomState(5)
    params = [
        ("input_shape", "3,10,11"), ("rand_crop", "1"),
        ("rand_mirror", "1"), ("max_random_contrast", "0.25"),
        ("max_random_illumination", "6"), ("mean_value", "4,5,6"),
        ("scale", "0.01"), ("seed_data", "13"),
    ]
    aug = _augmenter(params)
    assert aug.pil_path_ok() and not aug.tail_identity()
    insts = _rand_insts(rng)
    cropped = [
        aug.augment_pil(Image.fromarray(d.data), d.index, d.label, epoch=3)
        for d in insts
    ]
    assert all(c.data.dtype == np.uint8 for c in cropped)
    got = aug.augment_tail(cropped, epoch=3)
    want = [
        aug._augmented(d, apply_mean=True, rng=aug.record_rng(3, d.index))
        for d in insts
    ]
    for g, w_ in zip(got, want):
        assert g.data.tobytes() == w_.data.tobytes()


def test_mean_image_created_through_vectorized_pass(tmp_path):
    """First-run mean image: single pre-pool pass through the batch
    path, same value the serial per-record loop would produce, and the
    chain applies it."""
    imgs = np.ones((4, 8, 8, 3), np.float32) * np.arange(1, 5)[:, None, None, None]
    binp = str(tmp_path / "d.bin")
    w = BinPageWriter(binp)
    for im in imgs:
        w.push(encode_raw(im))
    w.close()
    lst = tmp_path / "d.lst"
    lst.write_text("".join(f"{i}\t0\tx.jpg\n" for i in range(4)))
    meanp = str(tmp_path / "mean.npz")
    it = _chain(binp, str(lst),
                aug=f'  raw_pixels = 1\n  image_mean = "{meanp}"\n',
                batch=4, shape="3,8,8")
    it.before_first()
    assert it.next()
    b = it.value()
    np.testing.assert_allclose(b.data[0], -1.5, rtol=1e-5)
    assert os.path.exists(meanp)
    with np.load(meanp) as z:
        np.testing.assert_allclose(z["mean"], 2.5, rtol=1e-6)
    it.close()


def test_pool_quarantines_corrupt_records(tmp_path):
    """A corrupt JPEG decoded by a pool worker is skipped and
    quarantined by the consumer in record order — same budget semantics
    as the serial reader."""
    binp, lst = _write_jpeg_imgbin(tmp_path, n=8, page_size=1 << 20)
    # flip bytes of one record's blob inside the single page
    blob = open(binp, "rb").read()
    frag = bytearray(blob)
    # CXBP: magic u32 | nrec u32 | lens | blobs — corrupt the 3rd blob
    import struct

    nrec = struct.unpack_from("<I", frag, 4)[0]
    lens = struct.unpack_from(f"<{nrec}I", frag, 8)
    start = 8 + 4 * nrec + sum(lens[:2])
    for off in range(start, start + 64):
        frag[off] ^= 0xFF
    open(binp, "wb").write(bytes(frag))

    it = _chain(
        binp, lst, aug="  rand_crop = 1\n",
        extra="  num_decode_workers = 2\n  decode_chunk = 3\n"
              "  max_bad_records = 2\n",
        batch=7,
    )
    it.before_first()
    seen = []
    while it.next():
        seen.append(it.value())
    got = {int(i) for b in seen for i in b.inst_index}
    assert 2 not in got or len(got) == 7  # record 2 skipped
    q = binp + ".quarantine"
    assert os.path.exists(q)
    assert open(q).read().splitlines()[0].startswith("2\t")
    it.close()


@pytest.mark.parametrize("workers", [0, 4])
def test_augment_epoch_anchor_reproduces_resume(tmp_path, workers):
    """`augment_epoch` (the CLI's per-round anchor) makes a FRESH
    process resumed at round r draw the exact stream an uninterrupted
    run drew at round r — epochs track training progress, not how many
    rewinds this process happened to make."""
    binp, lst = _write_jpeg_imgbin(tmp_path)
    extra = (f"  num_decode_workers = {workers}\n  decode_chunk = 3\n"
             if workers else "")
    a = _chain(binp, lst, extra=extra, round_batch=0)
    run_a = []
    for round_ in (1, 2, 3):  # uninterrupted rounds, anchored like cli
        a.before_first()
        a.set_param("augment_epoch", str(round_))
        while a.next():
            b = a.value()
            run_a.append((round_, b.data.tobytes()))
    a.close()
    # "resume": fresh iterator jumps straight to round 3
    b_it = _chain(binp, lst, extra=extra, round_batch=0)
    b_it.before_first()
    b_it.set_param("augment_epoch", "3")
    got = []
    while b_it.next():
        got.append((3, b_it.value().data.tobytes()))
    b_it.close()
    assert got == [x for x in run_a if x[0] == 3]


def test_pool_propagates_augment_errors(tmp_path):
    """An augmentation error (image smaller than the crop) RAISES in
    pool mode exactly like the serial path — it must not be laundered
    into the quarantine as a corrupt record."""
    binp, lst = _write_jpeg_imgbin(tmp_path, n=6, size=8)  # 8 < 12 crop
    it = _chain(binp, lst, aug="  rand_crop = 1\n",
                extra="  num_decode_workers = 2\n  max_bad_records = 99\n")
    it.before_first()
    with pytest.raises(ValueError, match="net input size"):
        while it.next():
            pass
    it.close()
    assert not os.path.exists(binp + ".quarantine")


def test_pool_watchdog_and_close_are_clean(tmp_path):
    """close() joins the workers; a second close is a no-op."""
    binp, lst = _write_jpeg_imgbin(tmp_path, n=6)
    it = _chain(binp, lst,
                extra="  num_decode_workers = 2\n")
    assert _epochs(it, 1)
    it.close()
    it.close()


# ----------------------------------------------------------------------
# persistent compile cache.  BOTH tests run in a SUBPROCESS: enabling
# jax's persistent compilation cache is process-global and permanent,
# and enabling it MID-PROCESS — after donated-buffer programs already
# compiled — intermittently corrupts later re-jitted programs on
# jaxlib 0.4.3x (silent numeric garbage or a SIGSEGV in
# batched_device_put).  Running these in-process was the root cause of
# tier-1's multi-file loop-gate flake (PR 8 bisect; see
# utils/compile_cache.py for the production-order guarantee).
def _run_py(script, cwd):
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo
    return subprocess.run(
        [sys.executable, "-c", script], capture_output=True, text=True,
        cwd=str(cwd), env=env, timeout=240,
    )


def test_compile_cache_dir_persists_programs(tmp_path):
    r = _run_py(f"""
import numpy as np
from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.io.data import DataBatch

cache_dir = {str(tmp_path / "xla_cache")!r}
cfg = [
    ("compile_cache_dir", cache_dir),
    ("dev", "cpu"), ("batch_size", "8"), ("input_shape", "1,1,6"),
    ("seed", "3"), ("eta", "0.1"),
    ("netconfig", "start"),
    ("layer[0->1]", "fullc:fc"), ("nhidden", "4"),
    ("layer[1->1]", "softmax"),
    ("netconfig", "end"),
]
tr = NetTrainer()
tr.set_params(cfg)
tr.init_model()
rng = np.random.RandomState(0)
tr.update(DataBatch(
    data=rng.randn(8, 6).astype(np.float32),
    label=rng.randint(0, 4, (8, 1)).astype(np.float32),
))
import os
entries = os.listdir(cache_dir)
assert entries, "persistent compile cache wrote no entries"
print("CACHE_OK", len(entries))
""", tmp_path)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "CACHE_OK" in r.stdout


def test_compile_cache_configure_scans_cfg(tmp_path):
    r = _run_py(f"""
from cxxnet_tpu.utils import compile_cache

d = {str(tmp_path / "cc")!r}
assert compile_cache.configure([("foo", "1"), ("compile_cache_dir", d)])
assert compile_cache.enabled_dir() == d
import os
assert os.path.isdir(d)
# idempotent
assert not compile_cache.configure([("compile_cache_dir", d)])
print("CONFIGURE_OK")
""", tmp_path)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "CONFIGURE_OK" in r.stdout


# ----------------------------------------------------------------------
# per-stage observability
def test_pipeline_stats_snapshot_schema(tmp_path):
    from cxxnet_tpu.utils.profiler import pipeline_stats

    binp, lst = _write_jpeg_imgbin(tmp_path)
    pipeline_stats().reset()
    it = _chain(binp, lst, extra="  num_decode_workers = 2\n")
    _epochs(it, 1)
    it.close()
    snap = pipeline_stats().snapshot()
    for stage in ("decode", "augment", "batch", "h2d", "device_wait"):
        assert stage in snap
        for field in ("count", "rows", "total_s", "rows_per_sec"):
            assert field in snap[stage]
    assert snap["decode"]["rows"] > 0
    assert snap["batch"]["rows"] > 0
    assert pipeline_stats().report()
    pipeline_stats().reset()
    assert pipeline_stats().snapshot()["decode"]["count"] == 0


def test_io_bench_smoke_schema(tmp_path):
    """The PERF=1 lane's contract: io_bench --smoke validates its own
    JSON schema (no throughput assertions)."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    from tools.io_bench import validate_report

    good = {
        "n_images": 4, "size": 8,
        "results": [{
            "mode": "serial", "img_per_sec": 1.0,
            "decode_augment_per_sec": 2.0,
            "stages": {s: {"count": 0, "rows": 0, "total_s": 0.0,
                           "rows_per_sec": 0.0}
                       for s in ("decode", "augment", "batch", "h2d",
                                 "device_wait")},
        }],
    }
    validate_report(good)
    bad = dict(good)
    bad["results"] = [dict(good["results"][0], img_per_sec=float("nan"))]
    with pytest.raises(ValueError):
        validate_report(bad)
