"""Serving subsystem tests: bucket cache, micro-batcher, engine, HTTP.

The compile-counting tests instrument ``FunctionalNet.forward`` — inside
a jitted function it runs only at TRACE time, so its call count equals
the number of XLA compilations triggered through the predict path.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from cxxnet_tpu import config as cfgmod
from cxxnet_tpu import serve
from cxxnet_tpu.nnet.trainer import NetTrainer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MLP_CFG = """
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 16
  init_sigma = 0.1
layer[+1:a1] = relu:a1
layer[a1->out] = fullc:fc2
  nhidden = 4
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,16
batch_size = 32
dev = cpu
eta = 0.1
"""


def make_trainer(seed=0, cfg=MLP_CFG):
    tr = NetTrainer()
    tr.set_params(cfgmod.parse_pairs(cfg))
    tr.set_param("seed", str(seed))
    tr.init_model()
    return tr


def count_traces(tr):
    """Wrap the net's forward so each XLA (re)trace bumps a counter."""
    calls = []
    orig = tr.net.forward

    def counting(*a, **k):
        calls.append(1)
        return orig(*a, **k)

    tr.net.forward = counting
    return calls


def toy_rows(n, seed=0):
    return np.random.RandomState(seed).randn(n, 16).astype(np.float32)


# ----------------------------------------------------------------------
# bucket policy + compile cache
def test_bucket_size_policy():
    assert [serve.bucket_size(n) for n in (1, 2, 3, 7, 8, 9, 100)] == [
        1, 2, 4, 8, 8, 16, 128,
    ]
    # rounded up to the mesh data-axis size so sharded predict stays legal
    assert serve.bucket_size(3, multiple_of=8) == 8
    assert serve.bucket_size(100, multiple_of=8) == 128
    with pytest.raises(ValueError):
        serve.bucket_size(0)


def test_compile_count_mixed_sizes():
    """Mixed request sizes {1,3,7,32,100} compile AT MOST once per
    power-of-two bucket; after warmup, zero new compiles."""
    tr = make_trainer()
    calls = count_traces(tr)
    cache = serve.ShapeBucketCache(tr, max_batch_size=128)
    sizes = [1, 3, 7, 32, 100]
    x = toy_rows(128)
    for n in sizes:
        out = cache.predict(x[:n])
        assert out.shape[0] == n
    buckets = {serve.bucket_size(n) for n in sizes}  # {1, 4, 8, 32, 128}
    warm = len(calls)
    assert warm <= len(buckets), (
        f"{warm} compiles for {len(buckets)} buckets"
    )
    # post-warmup: repeated mixed sizes, fresh data — NO new compiles
    for seed in (1, 2, 3):
        for n in sizes:
            cache.predict(toy_rows(n, seed=seed))
    assert len(calls) == warm, "post-warmup recompile detected"
    st = cache.stats()
    assert st["misses"] == len(sizes)  # one miss per first-seen bucket key
    assert st["hits"] == 3 * len(sizes)


def test_cache_trims_padding_and_matches_full_batch():
    tr = make_trainer()
    cache = serve.ShapeBucketCache(tr, max_batch_size=32)
    x = toy_rows(32)
    full = cache.predict(x)
    for n in (1, 3, 7, 30):
        out = cache.predict(x[:n])
        assert out.shape[0] == n  # bucket padding trimmed
        np.testing.assert_array_equal(out, full[:n])
    feats = cache.extract(x[:5], "fc1")
    assert feats.shape[0] == 5


def test_cache_sharded_mesh_buckets():
    """dev=cpu:0-7 (8 virtual devices): buckets round to the data-axis
    size and odd sizes still predict correctly through the sharded jit."""
    tr = make_trainer(cfg=MLP_CFG.replace("dev = cpu", "dev = cpu:0-7"))
    assert tr.mesh_plan.n_data == 8
    cache = serve.ShapeBucketCache(tr, max_batch_size=32)
    assert cache.bucket_for(3) == 8
    x = toy_rows(32)
    out = cache.predict(x[:3])
    assert out.shape[0] == 3
    np.testing.assert_array_equal(out, cache.predict(x)[:3])


# ----------------------------------------------------------------------
# micro-batcher
def test_batcher_coalesces_concurrent_requests():
    batches = []

    def runner(kind, node, data):
        batches.append(data.shape[0])
        time.sleep(0.01)  # widen the window so peers can join
        return data * 2.0

    b = serve.MicroBatcher(runner, max_batch_size=64, batch_timeout_ms=50,
                           queue_limit=64)
    xs = [np.full((1, 4), i, np.float32) for i in range(8)]
    outs = [None] * 8

    def go(i):
        outs[i] = b.submit(xs[i])

    threads = [threading.Thread(target=go, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    b.close()
    for i in range(8):
        np.testing.assert_array_equal(outs[i], xs[i] * 2.0)  # split right
    assert max(batches) > 1, f"no coalescing happened: {batches}"
    assert sum(batches) == 8


def test_batcher_load_shed_and_deadline():
    gate = threading.Event()

    def runner(kind, node, data):
        gate.wait(timeout=5)
        return data

    b = serve.MicroBatcher(runner, max_batch_size=4, batch_timeout_ms=0,
                           queue_limit=2)
    x = np.zeros((1, 4), np.float32)
    results = []
    t1 = threading.Thread(target=lambda: results.append(b.submit(x)))
    t1.start()
    time.sleep(0.05)  # worker picked req 1 up and is blocked in runner
    # a request whose deadline passes while queued is expired, not run
    err = []

    def late():
        try:
            b.submit(x, deadline_ms=10)
        except serve.DeadlineError as e:
            err.append(e)

    t2 = threading.Thread(target=late)
    t2.start()
    time.sleep(0.05)
    # queue now holds the deadline request; fill to the limit, then shed
    t3 = threading.Thread(target=lambda: b.submit(x))
    t3.start()
    time.sleep(0.05)
    with pytest.raises(serve.OverloadError):
        b.submit(x)
    gate.set()
    t1.join(5), t2.join(5), t3.join(5)
    b.close()
    assert len(err) == 1, "queued request should have expired"
    assert len(results) == 1


def test_batcher_close_fails_pending():
    gate = threading.Event()
    b = serve.MicroBatcher(lambda k, n, d: (gate.wait(5), d)[1],
                           max_batch_size=4, batch_timeout_ms=0,
                           queue_limit=8)
    x = np.zeros((1, 4), np.float32)
    threading.Thread(target=lambda: b.submit(x)).start()
    time.sleep(0.05)
    err = []

    def pending():
        try:
            b.submit(x)
        except serve.ClosedError as e:
            err.append(e)

    t = threading.Thread(target=pending)
    t.start()
    time.sleep(0.05)
    gate.set()
    b.close()
    t.join(5)
    assert len(err) == 1
    with pytest.raises(serve.ClosedError):
        b.submit(x)


# ----------------------------------------------------------------------
# engine
def test_engine_concurrent_submit_identical_to_sequential():
    """N threads through the micro-batcher get byte-identical results to
    sequential predict — coalescing and bucket padding must not change a
    single bit of any row."""
    tr = make_trainer()
    eng = serve.Engine(trainer=tr, max_batch_size=64, batch_timeout_ms=20,
                       queue_limit=256)
    sizes = [1, 3, 7, 5, 2, 1, 4, 6, 3, 1, 8, 2, 7, 5, 3, 2]
    datas = [toy_rows(n, seed=i) for i, n in enumerate(sizes)]
    seq = [eng.predict(d) for d in datas]  # warm + sequential reference
    outs = [None] * len(sizes)

    def go(i):
        outs[i] = eng.submit(datas[i])

    threads = [threading.Thread(target=go, args=(i,))
               for i in range(len(sizes))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i, (a, b) in enumerate(zip(seq, outs)):
        np.testing.assert_array_equal(a, b, err_msg=f"request {i}")
    st = eng.snapshot_stats()
    assert st["requests"] == 2 * len(sizes)
    assert st["ok"] == 2 * len(sizes)
    assert st["latency_ms"]["count"] == 2 * len(sizes)
    eng.close()


def test_engine_validates_input_shapes():
    eng = serve.Engine(trainer=make_trainer(), max_batch_size=8,
                       batch_timeout_ms=0)
    with pytest.raises(ValueError, match="row shape"):
        eng.predict(np.zeros((2, 5), np.float32))
    with pytest.raises(ValueError, match="kind"):
        eng.submit(toy_rows(1), kind="nope")
    with pytest.raises(ValueError, match="node"):
        eng.submit(toy_rows(1), kind="extract")
    # a single flat instance is promoted to a 1-row batch
    assert eng.predict(toy_rows(1)[0]).shape == (1,)
    # one request may not exceed max_batch_size rows (it would bypass
    # the queue bound and pad to an even larger bucket)
    with pytest.raises(ValueError, match="max_batch_size"):
        eng.predict(toy_rows(9))
    eng.close()
    with pytest.raises(serve.ClosedError):
        eng.predict(toy_rows(1))


def _save_round(tr, model_dir, round_):
    os.makedirs(model_dir, exist_ok=True)
    tr.round = round_
    tr.save_model(os.path.join(model_dir, f"{round_:04d}.model"))


def test_engine_loads_newest_valid_and_hot_reloads(tmp_path):
    mdir = str(tmp_path / "models")
    tr1 = make_trainer(seed=1)
    _save_round(tr1, mdir, 1)
    eng = serve.Engine(cfg=MLP_CFG, model_dir=mdir, max_batch_size=32,
                       batch_timeout_ms=0)
    assert eng.round == 1
    x = toy_rows(8)
    p1 = eng.submit(x, kind="scores")
    assert not eng.reload_if_newer()  # nothing newer yet

    tr2 = make_trainer(seed=2)  # different init → different scores
    _save_round(tr2, mdir, 2)
    # corrupt newer round must be skipped, not served
    with open(os.path.join(mdir, "0003.model"), "wb") as f:
        f.write(b"garbage not a model")
    assert eng.reload_if_newer()
    assert eng.round == 2
    assert eng.healthz()["round"] == 2
    p2 = eng.submit(x, kind="scores")
    assert not np.array_equal(p1, p2), "reload did not change the model"
    ref = serve.ShapeBucketCache(tr2, 32).scores(x)
    np.testing.assert_array_equal(p2, ref)
    eng.close()


def test_engine_reload_warms_served_buckets(tmp_path):
    """The post-swap model must already be compiled for every bucket in
    service — requests after a hot reload never stall on XLA compiles."""
    mdir = str(tmp_path / "models")
    _save_round(make_trainer(seed=1), mdir, 1)
    eng = serve.Engine(cfg=MLP_CFG, model_dir=mdir, max_batch_size=32,
                       batch_timeout_ms=0)
    eng.predict(toy_rows(3))   # bucket 4
    eng.predict(toy_rows(20))  # bucket 32
    _save_round(make_trainer(seed=2), mdir, 2)
    assert eng.reload_if_newer()
    calls = count_traces(eng.trainer)
    eng.predict(toy_rows(3))
    eng.predict(toy_rows(20))
    assert len(calls) == 0, "served buckets were not pre-warmed on reload"
    eng.close()


def test_engine_startup_falls_back_past_unloadable_checkpoint(tmp_path):
    """A garbage payload with a self-consistent manifest passes CRC
    validation but fails load_model; engine startup must fall back to
    the older loadable round instead of refusing to serve."""
    from cxxnet_tpu.utils import checkpoint as ckpt

    mdir = str(tmp_path / "models")
    _save_round(make_trainer(seed=1), mdir, 1)
    ckpt.write_checkpoint(os.path.join(mdir, "0002.model"),
                          b"garbage but manifested", round_=2, silent=True)
    eng = serve.Engine(cfg=MLP_CFG, model_dir=mdir, max_batch_size=8,
                       batch_timeout_ms=0)
    try:
        assert eng.round == 1
        assert eng.predict(toy_rows(2)).shape[0] == 2
    finally:
        eng.close()
    # nothing loadable at all → ModelLoadError naming the last failure
    only_bad = str(tmp_path / "bad_only")
    ckpt.write_checkpoint(os.path.join(only_bad, "0001.model"),
                          b"garbage", round_=1, silent=True)
    with pytest.raises(serve.ModelLoadError, match="no loadable"):
        serve.Engine(cfg=MLP_CFG, model_dir=only_bad)


def test_engine_rejects_invalid_model_in(tmp_path):
    bad = str(tmp_path / "bad.model")
    with open(bad, "wb") as f:
        f.write(b"garbage")
    with pytest.raises(serve.ModelLoadError):
        serve.Engine(cfg=MLP_CFG, model_in=bad)
    with pytest.raises(serve.ModelLoadError):
        serve.Engine(cfg=MLP_CFG, model_dir=str(tmp_path / "empty"))


# ----------------------------------------------------------------------
# HTTP front-end
def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as r:
        return json.loads(r.read())


def _post(port, path, obj):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(obj).encode("utf-8"),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read())


def test_http_endpoints_inprocess():
    tr = make_trainer()
    eng = serve.Engine(trainer=tr, max_batch_size=32, batch_timeout_ms=1)
    httpd = serve.make_server(eng, port=0)
    port = httpd.server_port
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        h = _get(port, "/healthz")
        assert h["status"] == "ok" and "net_fp" in h
        x = toy_rows(5)
        got = np.asarray(_post(port, "/predict", {"data": x.tolist()})["pred"])
        np.testing.assert_array_equal(got, eng.predict(x))
        raw = np.asarray(
            _post(port, "/predict", {"data": x.tolist(), "raw": True})
            ["scores"]
        )
        assert raw.shape == (5, 4)
        feats = np.asarray(
            _post(port, "/extract", {"data": x.tolist(), "node": "fc1"})
            ["features"]
        )
        assert feats.shape[0] == 5
        st = _get(port, "/statsz")
        for key in ("requests", "ok", "batch_fill_ratio", "latency_ms",
                    "compile_cache", "queue_depth"):
            assert key in st, key
        # error mapping: 404 route, 400 malformed / bad shape
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(port, "/nope")
        assert e.value.code == 404
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(port, "/predict", {"wrong": 1})
        assert e.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(port, "/predict", {"data": [[1.0, 2.0]]})
        assert e.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(port, "/extract", {"data": x.tolist()})
        assert e.value.code == 400
        # POST /reloadz: admin reload attempt (no model_dir here → a
        # clean noop), with the body drained so a kept-alive HTTP/1.1
        # connection stays in sync for the next request
        import http.client

        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        try:
            conn.request("POST", "/reloadz", body=b"{}",
                         headers={"Content-Type": "application/json"})
            r1 = conn.getresponse()
            body = json.loads(r1.read())
            assert r1.status == 200
            assert body["ok"] is True and body["swapped"] is False
            assert "breaker" in body and "round" in body
            # SAME connection: framing must not have desynced
            conn.request("GET", "/healthz")
            r2 = conn.getresponse()
            assert r2.status == 200
            assert json.loads(r2.read())["status"] == "ok"
        finally:
            conn.close()
    finally:
        httpd.shutdown()
        httpd.server_close()
        eng.close()


# ----------------------------------------------------------------------
# CLI task=serve smoke (ephemeral port, clean shutdown)
SERVE_CONF = """
data = train
iter = synthetic
  nsample = 64
  input_shape = 1,1,16
  nclass = 4
iter = end

netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 16
  init_sigma = 0.1
layer[+1:a1] = relu:a1
layer[a1->out] = fullc:fc2
  nhidden = 4
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end

input_shape = 1,1,16
batch_size = 32
dev = cpu
eta = 0.1
num_round = 1
save_model = 1
eval_train = 1
metric = error
model_dir = MODELDIR
print_step = 0
"""


def test_cli_serve_smoke(tmp_path):
    from conftest import run_cli

    conf = tmp_path / "serve.conf"
    conf.write_text(SERVE_CONF.replace("MODELDIR", str(tmp_path / "models")))
    r = run_cli([str(conf)], str(tmp_path))
    assert r.returncode == 0, r.stderr + r.stdout

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    proc = subprocess.Popen(
        [sys.executable, "-m", "cxxnet_tpu", str(conf), "task=serve",
         "serve_port=0", "silent=1", "batch_timeout_ms=1"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=str(tmp_path), env=env,
    )
    lines = []

    def _pump():
        for line in proc.stdout:
            lines.append(line)

    reader = threading.Thread(target=_pump, daemon=True)
    reader.start()
    try:
        port = None
        deadline = time.time() + 120
        while time.time() < deadline and port is None:
            for line in list(lines):
                if "serving model round" in line and "http://" in line:
                    port = int(line.rsplit(":", 1)[1])
                    break
            if proc.poll() is not None:
                raise AssertionError("server died:\n" + "".join(lines))
            time.sleep(0.1)
        assert port is not None, "server never reported its port:\n" + (
            "".join(lines)
        )
        h = _get(port, "/healthz")
        assert h["status"] == "ok" and h["round"] == 1
        x = toy_rows(3)
        pred = _post(port, "/predict", {"data": x.tolist()})["pred"]
        assert len(pred) == 3
        st = _get(port, "/statsz")
        assert st["ok"] >= 1
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            raise
    reader.join(timeout=5)
    out = "".join(lines)
    assert proc.returncode == 0, out
    assert "shutdown complete" in out


# ----------------------------------------------------------------------
# resilience: graceful drain + reload circuit breaker
@pytest.mark.chaos
def test_drain_under_load_completes_inflight_requests(tmp_path):
    """SIGTERM-equivalent shutdown while requests are mid-flight: the
    server stops accepting but every admitted request still gets its
    200 before serve_forever returns (drain_timeout_s window).  The
    model is slowed via the serve.batch latency injection so requests
    are reliably in flight at shutdown time."""
    from cxxnet_tpu.utils import faults

    tr = make_trainer()
    eng = serve.Engine(trainer=tr, max_batch_size=8, batch_timeout_ms=50,
                       queue_limit=64)
    eng.predict(toy_rows(1))  # warm the compile path first
    faults.injector().latency_s = 0.3
    faults.install("serve.batch:latency:1")
    box = {}
    ready = threading.Event()

    def _run():
        serve.serve_forever(
            eng, port=0, drain_timeout_s=10.0,
            ready_fn=lambda h: (box.update(httpd=h), ready.set()),
        )
        box["returned"] = True

    srv = threading.Thread(target=_run, daemon=True)
    srv.start()
    assert ready.wait(10)
    httpd = box["httpd"]
    port = httpd.server_port
    n = 8
    results, errors = [None] * n, [None] * n

    def _req(i):
        try:
            results[i] = _post(port, "/predict",
                               {"data": toy_rows(1, seed=i).tolist()})
        except Exception as e:  # noqa: BLE001 - recorded for the assert
            errors[i] = e

    threads = [threading.Thread(target=_req, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    # wait until every request is admitted and in flight, then shut down
    deadline = time.time() + 5
    while time.time() < deadline and httpd.inflight.count < n:
        time.sleep(0.005)
    assert httpd.inflight.count > 0, "requests never went in flight"
    httpd.shutdown()
    for t in threads:
        t.join(timeout=15)
    srv.join(timeout=15)
    assert box.get("returned"), "serve_forever did not return"
    assert errors == [None] * n, f"dropped in-flight requests: {errors}"
    assert all(r is not None and len(r["pred"]) == 1 for r in results)
    faults.reset()
    eng.close()


def test_reload_breaker_keeps_old_model_serving(tmp_path):
    """A checkpoint that validates (CRC-correct) but fails to LOAD must
    not take the server down: the breaker opens after the configured
    consecutive failures, the old model keeps answering, /healthz turns
    degraded and /statsz counts the failures; a later good checkpoint
    recovers through the half-open trial."""
    from cxxnet_tpu.utils import checkpoint as ckpt

    mdir = str(tmp_path / "models")
    tr1 = make_trainer(seed=1)
    _save_round(tr1, mdir, 1)
    eng = serve.Engine(cfg=MLP_CFG, model_dir=mdir, max_batch_size=8,
                       batch_timeout_ms=0, reload_breaker_threshold=2,
                       reload_breaker_cooldown_s=30.0)
    try:
        x = toy_rows(4)
        p1 = eng.submit(x, kind="scores")
        # round 2: garbage payload WITH a consistent manifest — passes
        # validation, explodes in load_model
        os.makedirs(mdir, exist_ok=True)
        ckpt.write_checkpoint(os.path.join(mdir, "0002.model"),
                              b"not a model at all", round_=2, silent=True)
        assert not eng.try_reload()
        assert eng.reload_breaker.state == "closed"  # 1 of 2 failures
        assert not eng.try_reload()
        assert eng.reload_breaker.state == "open"
        h = eng.healthz()
        assert h["status"] == "degraded" and h["round"] == 1
        # machine-readable degrade cause: the fleet supervisor (and any
        # external LB) parses the reasons token, not the status string
        assert h["reasons"] == ["reload_breaker_open"]
        np.testing.assert_array_equal(eng.submit(x, kind="scores"), p1)
        st = eng.snapshot_stats()
        assert st["reload_failures"] == 2
        assert st["last_reload_ok"] is False
        assert st["reload_breaker"]["state"] == "open"
        # while open, polls don't even attempt the reload
        assert not eng.try_reload()
        assert st["reload_failures"] == eng.snapshot_stats()["reload_failures"]
        # a good round 3 lands; cooldown expires → half-open trial swaps
        _save_round(make_trainer(seed=3), mdir, 3)
        eng.reload_breaker.cooldown_s = 0.0
        assert eng.try_reload()
        assert eng.round == 3
        assert eng.healthz()["status"] == "ok"
        assert eng.snapshot_stats()["reload_swaps"] == 1
        assert not np.array_equal(eng.submit(x, kind="scores"), p1)
    finally:
        eng.close()


def test_healthz_reasons_shape(tmp_path):
    """Single-engine /healthz carries the machine-readable ``reasons``
    list next to the legacy fields: empty when ok, one stable token per
    degrade condition, and the shape ``tools/obs_dump.py --check
    --healthz`` validates (the fleet supervisor's probe contract)."""
    eng = serve.Engine(trainer=make_trainer(), max_batch_size=8,
                       batch_timeout_ms=0)
    try:
        h = eng.healthz()
        assert h["status"] == "ok" and h["reasons"] == []
        # legacy fields stay for pre-fleet scrapers
        assert h["reload_breaker"] == "closed"
        assert "round" in h and "model" in h and "quant" in h

        hz = tmp_path / "healthz.json"
        hz.write_text(json.dumps(h))
        from conftest import run_cli

        r = run_cli([os.path.join(REPO, "tools", "obs_dump.py"),
                     "--check", "--healthz", str(hz)],
                    cwd=str(tmp_path), module=False)
        assert r.returncode == 0, r.stdout + r.stderr

        # an armed alert degrades WITH a named token
        from cxxnet_tpu.obs import alerts as obs_alerts
        from cxxnet_tpu.obs.registry import registry as obs_registry

        obs_registry().gauge(
            "serve_test_reasons_gauge", "test").set(5.0)
        ev = obs_alerts.evaluator()
        ev.add_rule(obs_alerts.parse_rule(
            "reasons_probe:serve_test_reasons_gauge:>:1"))
        ev.evaluate_once()
        try:
            h = eng.healthz()
            assert h["status"] == "degraded"
            assert "alert:reasons_probe" in h["reasons"]
            hz.write_text(json.dumps(h))
            r = run_cli([os.path.join(REPO, "tools", "obs_dump.py"),
                         "--check", "--healthz", str(hz)],
                        cwd=str(tmp_path), module=False)
            assert r.returncode == 0, r.stdout + r.stderr
        finally:
            obs_alerts.reset()
    finally:
        eng.close()


# ----------------------------------------------------------------------
# serving metrics
def test_percentile_tracker():
    from cxxnet_tpu.utils.profiler import PercentileTracker

    t = PercentileTracker(window=100)
    assert t.percentiles() == {} and t.summary() == {"count": 0}
    for v in range(1, 101):
        t.add(v / 1000.0)
    s = t.summary(scale=1e3)
    assert s["count"] == 100
    assert 45 <= s["p50"] <= 55
    assert 90 <= s["p95"] <= 99
    assert 95 <= s["p99"] <= 100
    for v in range(200):  # window slides: old samples age out
        t.add(1.0)
    assert t.percentiles()["p50"] == 1.0
    assert t.count == 300


def test_serving_stats_fill_ratio():
    from cxxnet_tpu.serve.metrics import ServingStats

    s = ServingStats()
    s.record_batch(rows=6, bucket_rows=8)
    s.record_batch(rows=8, bucket_rows=8)
    snap = s.snapshot()
    assert snap["batches"] == 2
    assert snap["batch_fill_ratio"] == pytest.approx(14 / 16)
    assert snap["rows_per_batch"] == pytest.approx(7.0)


@pytest.mark.slow
def test_batched_throughput_beats_sequential():
    """Acceptance: micro-batched throughput at concurrency 16 >= 3x the
    sequential single-request rate on the synthetic MLP."""
    tr = make_trainer()
    eng = serve.Engine(trainer=tr, max_batch_size=64, batch_timeout_ms=5,
                       queue_limit=1024)
    x = toy_rows(1)
    for _ in range(4):
        eng.predict(x)  # warm bucket 1 + bucket paths

    n_seq = 50
    t0 = time.perf_counter()
    for _ in range(n_seq):
        eng.predict(x)
    seq_rate = n_seq / (time.perf_counter() - t0)

    n_each, n_thread = 50, 16

    def go():
        for _ in range(n_each):
            eng.predict(x)

    threads = [threading.Thread(target=go) for _ in range(n_thread)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    conc_rate = n_each * n_thread / (time.perf_counter() - t0)
    eng.close()
    assert conc_rate >= 3 * seq_rate, (
        f"batched {conc_rate:.0f} req/s vs sequential {seq_rate:.0f} req/s"
    )
