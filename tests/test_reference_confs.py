"""The reference's shipped example confs are the grammar fixture
(SURVEY §4.5): they must tokenize, section-split, and — where the layer
graph is complete — build a net with correct shapes.  Data files are
absent, so only parsing/graph construction is exercised, never IO.
"""

import os

import pytest

from cxxnet_tpu import config as C
from cxxnet_tpu.nnet.trainer import NetTrainer

REF = "/root/reference/example"

ALL_CONFS = [
    "MNIST/MNIST.conf",
    "MNIST/MNIST_CONV.conf",
    "MNIST/mpi.conf",
    "ImageNet/ImageNet.conf",
    "kaggle_bowl/bowl.conf",
    "kaggle_bowl/pred.conf",
]


@pytest.mark.parametrize("rel", ALL_CONFS)
def test_reference_conf_parses(rel):
    path = os.path.join(REF, rel)
    if not os.path.exists(path):
        pytest.skip(f"{rel} not present")
    cfg = C.parse_file(path)
    assert cfg, f"{rel}: no pairs parsed"
    split = C.split_sections(cfg)
    # every opened iterator section must have been closed by iter=end
    for sec in split.sections:
        assert sec.entries is not None


@pytest.mark.parametrize(
    "rel,nclass",
    [("MNIST/MNIST.conf", 10), ("MNIST/MNIST_CONV.conf", 10),
     ("ImageNet/ImageNet.conf", 1000), ("kaggle_bowl/bowl.conf", 121)],
)
def test_reference_conf_builds_net(rel, nclass):
    """The netconfig sections build, shape-infer, and end in the right
    class count on this framework unchanged."""
    path = os.path.join(REF, rel)
    if not os.path.exists(path):
        pytest.skip(f"{rel} not present")
    cfg = C.split_sections(C.parse_file(path)).global_entries
    tr = NetTrainer()
    tr.set_params(cfg)
    tr.set_param("dev", "cpu")
    tr.set_param("batch_size", "4")  # tiny for CPU shape inference
    tr.init_model()
    out = tr.net.node_shapes[tr.net.out_node_index()]
    assert out[-1] == nclass, f"{rel}: output {out}"


REPO_EXAMPLES = [
    ("MNIST/MNIST.conf", 10),
    ("MNIST/MNIST_CONV.conf", 10),
    ("MNIST/digits.conf", 10),
    ("MNIST/dist.conf", 10),
    ("ImageNet/alexnet.conf", 1000),
    ("ImageNet/googlenet.conf", 1000),
    ("ImageNet/vgg16.conf", 1000),
    ("kaggle_bowl/bowl.conf", 121),
]


@pytest.mark.parametrize("rel,nclass", REPO_EXAMPLES)
def test_repo_example_conf_builds_net(rel, nclass):
    """This repo's shipped example confs stay buildable with correct
    output class counts (the dist.conf case strips the distributed
    launch keys — joining a job needs real peers)."""
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "example", rel)
    cfg = [
        (k, v)
        for k, v in C.split_sections(C.parse_file(path)).global_entries
        if not k.startswith("dist_")
    ]
    tr = NetTrainer()
    tr.set_params(cfg)
    tr.set_param("dev", "cpu")
    tr.set_param("batch_size", "4")
    tr.init_model()
    out = tr.net.node_shapes[tr.net.out_node_index()]
    assert out[-1] == nclass, f"{rel}: output {out}"


def test_reference_only_keys_accepted():
    """The reference's GPU/PS-specific knobs (cuDNN `algo`, mshadow
    layout `force_contiguous`, async-PS `bigarray_bound` /
    `init_on_worker` / `pull_at_backprop`, vestigial `net_type` /
    `reset_net_type` — cxxnet_main.cpp:85-86, CreateNet_ always returns
    the one trainer) parse and train without error: on TPU they are
    no-ops by design (XLA autotunes convs; SPMD replaces the parameter
    server).  `test_on_server` is NOT a no-op — the CLI implements it
    as the per-round cross-process weight-sync check
    (tests/test_distributed.py)."""
    import numpy as np

    from cxxnet_tpu.io.data import DataBatch

    conf = """
netconfig = start
layer[0->1] = conv:cv
  nchannel = 4
  kernel_size = 1
  algo = 1
layer[1->2] = flatten:fl
layer[2->3] = fullc:fc2
  nhidden = 4
  force_contiguous = 1
layer[3->3] = softmax:sm
netconfig = end
input_shape = 1,4,4
batch_size = 8
dev = cpu
updater = sgd
eta = 0.01
net_type = 0
reset_net_type = 0
bigarray_bound = 1000000
init_on_worker = 1
pull_at_backprop = 1
test_on_server = 0
param_server = local
"""
    tr = NetTrainer()
    tr.set_params(C.parse_pairs(conf))
    tr.init_model()
    b = DataBatch(data=np.random.RandomState(0).randn(8, 4, 4, 1)
                  .astype("float32"),
                  label=np.zeros((8, 1), "float32"))
    tr.update(b)
