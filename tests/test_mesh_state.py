"""SPMD train-state placement: the ZeRO memory win, measured.

ROADMAP item 1 acceptance: on an N-way data mesh with sharded weight
update, the per-device resident bytes of params + updater state drop to
~1/N of the replicated footprint; the fused step really donates its
input buffers (weights update in place); a checkpoint written on one
mesh re-shards onto the CURRENT mesh at load.  All CPU-measurable via
``addressable_shards`` — no TPU required.
"""

import numpy as np
import pytest
import jax

from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.io.data import DataBatch

# every param/state dim divides 8, so a zero=3 run shards EVERYTHING
# and the per-device floor is exactly 1/8 of the replicated total
MLP8_CFG = [
    ("dev", "tpu:0-7"),
    ("batch_size", "16"),
    ("input_shape", "1,1,16"),
    ("seed", "7"),
    ("eta", "0.1"),
    ("momentum", "0.9"),
    ("netconfig", "start"),
    ("layer[0->1]", "fullc:fc1"),
    ("nhidden", "128"),
    ("layer[1->2]", "sigmoid"),
    ("layer[2->3]", "fullc:fc2"),
    ("nhidden", "8"),
    ("layer[3->3]", "softmax"),
    ("netconfig", "end"),
]


def _build(extra=()):
    tr = NetTrainer()
    tr.set_params(list(MLP8_CFG) + list(extra))
    tr.init_model()
    return tr


def _step(tr, seed=0):
    rng = np.random.RandomState(seed)
    tr.update(DataBatch(
        data=rng.randn(16, 16).astype(np.float32),
        label=rng.randint(0, 8, (16, 1)).astype(np.float32),
    ))


def test_state_placed_on_mesh_at_init():
    """zero=1: updater state lives data-axis-sharded BEFORE any step —
    placement happens at init, not as a side effect of the first
    donated program run."""
    tr = _build([("shard_weight_update", "1")])
    m = tr.ustates["l0_fc1"]["wmat"]["m"]
    assert "data" in tuple(m.sharding.spec)
    assert m.addressable_shards[0].data.shape[0] == m.shape[0] // 8
    # params stay replicated under ZeRO-1, but are explicitly placed
    w = tr.params["l0_fc1"]["wmat"]
    assert w.sharding.is_fully_replicated
    assert len(w.sharding.device_set) == 8


def test_memory_win_zero3_is_one_over_n():
    """The acceptance number: with the weight update AND params sharded
    (zero=3) on the 8-way data mesh, per-device params+state bytes are
    <= ~(1/N + eps) of the replicated total."""
    tr = _build([("zero", "3")])
    per_device, total = tr.state_shard_bytes()
    assert len(per_device) == 8
    worst = max(per_device.values())
    assert worst <= total / 8 * 1.01 + 64, (
        f"per-device {worst} bytes vs replicated total {total} "
        f"(expected ~1/8)"
    )
    # survives a real step (out_shardings keep the placement)
    _step(tr)
    per_device2, total2 = tr.state_shard_bytes()
    assert total2 == total
    assert max(per_device2.values()) <= total / 8 * 1.01 + 64


def test_memory_win_zero1_shards_state_only():
    """zero=1: updater state is 1/N per device, params replicated —
    per-device sits at params_total + ustate_total/N."""
    tr = _build([("shard_weight_update", "1")])
    p_total = sum(leaf.nbytes
                  for leaf in jax.tree_util.tree_leaves(tr.params))
    u_total = sum(leaf.nbytes
                  for leaf in jax.tree_util.tree_leaves(tr.ustates))
    per_device, total = tr.state_shard_bytes()
    assert total == p_total + u_total
    worst = max(per_device.values())
    assert worst <= p_total + u_total / 8 * 1.01 + 64
    # and the replicated baseline really is bigger: the win is ~u_total
    tr_rep = _build()
    worst_rep = max(tr_rep.state_shard_bytes()[0].values())
    assert worst_rep == total  # replicated: a full copy per device
    assert worst < worst_rep


def test_state_bytes_gauge_exported():
    """train_state_shard_bytes{device} / train_state_total_bytes land in
    the shared registry at placement time (the scrape-visible form of
    the memory win)."""
    from cxxnet_tpu.obs.registry import registry

    tr = _build([("zero", "3")])
    per_device, total = tr.state_shard_bytes()
    snap = registry().snapshot()
    shard_g = snap.get("train_state_shard_bytes")
    total_g = snap.get("train_state_total_bytes")
    assert shard_g is not None and total_g is not None
    assert list(total_g.values())[0] == float(total)
    for dev, v in per_device.items():
        key = f'train_state_shard_bytes{{device="{dev}"}}'
        assert shard_g[key] == float(v)


def test_fused_step_donates_buffers():
    """donate_argnums on (params, ustates, aux): after one fused step
    the previous weight/state buffers are deleted — the weights really
    updated in place rather than doubling peak memory."""
    tr = _build([("zero", "3")])
    old_w = tr.params["l0_fc1"]["wmat"]
    old_m = tr.ustates["l0_fc1"]["wmat"]["m"]
    _step(tr)
    assert old_w.is_deleted(), "param buffer not donated"
    assert old_m.is_deleted(), "updater-state buffer not donated"
    assert not tr.params["l0_fc1"]["wmat"].is_deleted()


def test_shard_weight_update_key():
    tr = NetTrainer()
    tr.set_param("shard_weight_update", "1")
    assert tr.zero == 1
    tr.set_param("shard_weight_update", "0")
    assert tr.zero == 0
    with pytest.raises(ValueError, match="shard_weight_update"):
        tr.set_param("shard_weight_update", "2")


def test_shard_weight_update_matches_replicated():
    """The sharded weight update is placement, not math: same weights
    as the replicated-update run, same seed, 5 steps."""
    a = _build()
    b = _build([("shard_weight_update", "1")])
    rng_a, rng_b = np.random.RandomState(3), np.random.RandomState(3)
    for rng, tr in ((rng_a, a), (rng_b, b)):
        for _ in range(5):
            tr.update(DataBatch(
                data=rng.randn(16, 16).astype(np.float32),
                label=rng.randint(0, 8, (16, 1)).astype(np.float32),
            ))
    for key in a.params:
        for tag in a.params[key]:
            np.testing.assert_allclose(
                np.asarray(a.params[key][tag]),
                np.asarray(b.params[key][tag]),
                rtol=2e-4, atol=2e-5,
                err_msg=f"{key}/{tag} diverged (sharded vs replicated "
                        "weight update)",
            )


def test_checkpoint_reshards_onto_current_mesh(tmp_path):
    """save on the 8-way zero=3 mesh -> load into a 4-way zero=1
    trainer: the restored state lands sharded per the CURRENT mesh
    (placement follows the loader's plan, not the writer's), and the
    updater state rides along bit-exactly (save_ustate=1)."""
    a = _build([("zero", "3"), ("save_ustate", "1")])
    _step(a)
    path = str(tmp_path / "m.model")
    a.save_model(path, round_=0)

    b = NetTrainer()
    b.set_params(
        [(k, "tpu:0-3" if k == "dev" else v) for k, v in MLP8_CFG]
        + [("shard_weight_update", "1"), ("save_ustate", "1")]
    )
    b.load_model(path)
    w = b.params["l0_fc1"]["wmat"]
    assert w.sharding.is_fully_replicated          # zero=1: params whole
    assert len(w.sharding.device_set) == 4         # ...on the NEW mesh
    m = b.ustates["l0_fc1"]["wmat"]["m"]
    assert "data" in tuple(m.sharding.spec)
    assert m.addressable_shards[0].data.shape[0] == m.shape[0] // 4
    np.testing.assert_array_equal(
        np.asarray(a.params["l0_fc1"]["wmat"]), np.asarray(w))
    np.testing.assert_array_equal(
        np.asarray(a.ustates["l0_fc1"]["wmat"]["m"]), np.asarray(m))
    # and the resharded trainer still trains
    _step(b, seed=1)
    assert b.epoch_counter == 2


def _grow_src_trainer():
    """A 4-way zero=1 trainer with one step of momentum in its state —
    the SMALLER mesh a growing pod reshards FROM (save_ustate so the
    updater state's bit-equality is provable through the round trip)."""
    tr = NetTrainer()
    tr.set_params(
        [(k, "tpu:0-3" if k == "dev" else v) for k, v in MLP8_CFG]
        + [("shard_weight_update", "1"), ("save_ustate", "1")]
    )
    tr.init_model()
    _step(tr)
    return tr


@pytest.mark.parametrize("zero", [1, 3])
def test_checkpoint_reshards_onto_larger_mesh(tmp_path, zero):
    """Mesh GROWTH (the elastic rejoin path): a checkpoint written on
    the 4-way mesh loads into an 8-way trainer — zero=1 keeps params
    whole on the new mesh with updater state sharded 8 ways; zero=3
    shards the params themselves — and every restored leaf is
    bit-equal.  The shrink direction is covered by
    test_checkpoint_reshards_onto_current_mesh above."""
    a = _grow_src_trainer()
    path = str(tmp_path / "grow.model")
    a.save_model(path, round_=0)

    b = NetTrainer()
    b.set_params(list(MLP8_CFG)
                 + [("zero", str(zero)), ("save_ustate", "1")])
    b.load_model(path)
    w = b.params["l0_fc1"]["wmat"]
    assert len(w.sharding.device_set) == 8      # ...on the LARGER mesh
    if zero == 1:
        assert w.sharding.is_fully_replicated
    else:
        assert "data" in tuple(w.sharding.spec)  # FSDP: params sharded
        assert w.addressable_shards[0].data.shape[0] == w.shape[0] // 8
    m = b.ustates["l0_fc1"]["wmat"]["m"]
    assert "data" in tuple(m.sharding.spec)
    assert m.addressable_shards[0].data.shape[0] == m.shape[0] // 8
    np.testing.assert_array_equal(
        np.asarray(a.params["l0_fc1"]["wmat"]), np.asarray(w))
    np.testing.assert_array_equal(
        np.asarray(a.ustates["l0_fc1"]["wmat"]["m"]), np.asarray(m))
    # and the grown trainer still trains with donated buffers intact
    _step(b, seed=1)
    assert b.epoch_counter == 2


def test_zero3_one_program_gathers_and_aliases():
    """The one-program claim in the compiled HLO: the zero=3 fused step
    (a) all-gathers param shards just-in-time (gather-before-use — no
    resident full replica), (b) aliases its donated inputs to outputs
    (``input_output_alias`` — the in-place weight update), and (c) is
    ONE program: repeated steps never re-jit (no per-replica programs).
    The reduce-scatter spelling of the gradient combine is a partitioner
    choice this CPU backend lowers as all-reduce + local slice; the
    shard-resident-state property it buys is asserted by the memory
    tests above, so the HLO check pins only backend-stable facts."""
    import jax.numpy as jnp

    tr = _build([("zero", "3")])
    fn = tr._fused_step_fn()
    rng = np.random.RandomState(0)
    d = jnp.asarray(rng.randn(16, 16).astype(np.float32))
    lbl = jnp.asarray(rng.randint(0, 8, (16, 1)).astype(np.float32))
    mask = jnp.asarray(np.ones(16, np.float32))
    txt = fn.lower(
        tr.params, tr.ustates, tr.aux, d, lbl, mask,
        jax.random.PRNGKey(0), jnp.asarray(0, jnp.int32), (),
    ).compile().as_text()
    assert "all-gather" in txt, "zero=3 step should gather-before-use"
    assert "input_output_alias" in txt, "donated buffers should alias"
    # (c): 5 updates reuse ONE cached fused program
    for i in range(5):
        _step(tr, seed=i)
    assert list(tr._jit_cache) == ["fused"], (
        f"expected exactly one cached step program, got "
        f"{list(tr._jit_cache)}"
    )


# ----------------------------------------------------------------------
# integrity-plane fingerprints (doc/robustness.md "Integrity plane")
def _digest_leaves(tr):
    """Per-tensor global digests over params + updater state — the
    layout-independent identity the replica vote compares."""
    from cxxnet_tpu.integrity.fingerprint import digest_global

    out = {}
    for key in sorted(tr.params):
        for tag in sorted(tr.params[key]):
            out[f"{key}/{tag}"] = digest_global(tr.params[key][tag])
    for key in sorted(tr.ustates):
        for tag in sorted(tr.ustates[key]):
            for slot in sorted(tr.ustates[key][tag]):
                out[f"ust:{key}/{tag}@{slot}"] = digest_global(
                    tr.ustates[key][tag][slot])
    return out


def test_fingerprints_are_mesh_layout_invariant(tmp_path):
    """The state fingerprint is a pure function of the LOGICAL tensor:
    one checkpoint loaded onto a 1-device mesh, the 4-way zero=1 mesh
    and the 8-way zero=3 mesh digests identically per tensor (the
    position-weighted modular sums commute across any slicing), so
    cross-mesh replicas can vote without ever gathering the floats."""
    src = _grow_src_trainer()
    path = str(tmp_path / "fp.model")
    src.save_model(path, round_=0)

    def load(dev, extra):
        tr = NetTrainer()
        tr.set_params(
            [(k, dev if k == "dev" else v) for k, v in MLP8_CFG]
            + [("save_ustate", "1")] + list(extra)
        )
        tr.load_model(path)
        return tr

    one = _digest_leaves(load("tpu:0", []))
    four = _digest_leaves(load("tpu:0-3", [("shard_weight_update", "1")]))
    eight = _digest_leaves(load("tpu:0-7", [("zero", "3")]))
    assert set(one) == set(four) == set(eight)
    assert any(k.startswith("ust:") for k in one)  # ustate rides along
    assert one == four, "1-device vs 4-way zero=1 digests diverge"
    assert one == eight, "1-device vs 8-way zero=3 digests diverge"


def test_fingerprint_jit_matches_numpy_oracle():
    """The jitted on-device digest program and the pure-numpy oracle
    agree per shard AND per combined tensor — the cross-implementation
    check that makes a digest mismatch attributable to the DATA, not
    to the digest pipeline."""
    from cxxnet_tpu.integrity.fingerprint import (
        combine_digests, digest_array, digest_device_array, digest_global,
    )

    tr = _build([("zero", "3"), ("save_ustate", "1")])
    _step(tr)
    for arr in (tr.params["l0_fc1"]["wmat"],
                tr.ustates["l0_fc1"]["wmat"]["m"],
                tr.params["l2_fc2"]["bias"]):
        whole = np.asarray(arr)
        assert digest_global(arr) == digest_array(whole)
        parts = [
            digest_device_array(s.data, index=s.index, shape=arr.shape)
            for s in arr.addressable_shards
        ]
        oracle = [
            digest_array(np.asarray(s.data), index=s.index,
                         shape=arr.shape)
            for s in arr.addressable_shards
        ]
        assert parts == oracle
        distinct = {}
        for s, d in zip(arr.addressable_shards, parts):
            distinct.setdefault(
                tuple((sl.start, sl.stop, sl.step) for sl in s.index), d)
        assert combine_digests(distinct.values()) == digest_array(whole)
