"""Unit tests for utils/metric.py — rec@n semantics incl. the
reference's random tie-break (src/utils/metric.h:150-170)."""

import numpy as np

from cxxnet_tpu.utils.metric import create_metric


def _score(name, pred, label):
    m = create_metric(name)
    m.add_eval(pred, label)
    return m.get()


def test_rec_at_1_matches_accuracy_on_distinct_scores():
    pred = np.array(
        [[0.1, 0.7, 0.2], [0.9, 0.05, 0.05], [0.2, 0.3, 0.5]], np.float32
    )
    label = np.array([[1.0], [2.0], [2.0]], np.float32)
    assert _score("rec@1", pred, label) == 2.0 / 3.0


def test_rec_at_n_multi_label_list():
    # label_width 2: fraction of the label list found in the top-n
    pred = np.array([[0.4, 0.3, 0.2, 0.1]], np.float32)
    label = np.array([[0.0, 3.0]], np.float32)  # one in top-2, one not
    assert _score("rec@2", pred, label) == 0.5


def test_rec_at_n_random_tiebreak_spreads_equal_scores():
    # all scores equal: a deterministic argsort would always pick class
    # 0, scoring exactly 1.0 for label 0 and 0.0 for any other label.
    # The reference shuffles before sorting; with 200 instances labelled
    # class 7 of 10, random tie-break recalls ~1/10, never 0 or 1.
    n, c = 200, 10
    pred = np.ones((n, c), np.float32)
    label = np.full((n, 1), 7.0, np.float32)
    got = _score("rec@1", pred, label)
    assert 0.0 < got < 1.0
    assert abs(got - 1.0 / c) < 0.1

    # seeded: two fresh metric instances agree exactly
    assert got == _score("rec@1", pred, label)


def test_rec_at_n_tiebreak_keeps_clear_winners():
    # random tie-break must not disturb strictly ordered scores
    rng = np.random.RandomState(3)
    pred = rng.rand(64, 12).astype(np.float32)
    label = np.argmax(pred, axis=1).astype(np.float32)[:, None]
    assert _score("rec@1", pred, label) == 1.0
