"""Model zoo: every builder parses, shape-infers, and takes a train step.

The reference's examples ARE its regression suite (SURVEY §4.5); these
tests are the equivalent for the generated model confs — including
GoogLeNet, the BASELINE.json benchmark model.
"""

import numpy as np
import pytest

from cxxnet_tpu import config as cfgmod
from cxxnet_tpu.models import MODEL_BUILDERS
from cxxnet_tpu.nnet.trainer import NetTrainer


def _global_cfg(conf_text: str):
    """Netconfig + globals only — iterator sections stripped the way the
    CLI does before handing entries to the trainer."""
    return cfgmod.split_sections(cfgmod.parse_pairs(conf_text)).global_entries


@pytest.mark.parametrize("name", sorted(MODEL_BUILDERS))
def test_model_shapes(name):
    """Parse + init at tiny batch; checks graph wiring and shape rules."""
    builder = MODEL_BUILDERS[name]
    if name.startswith("mnist") or name in ("kaggle_bowl", "transformer_lm"):
        text = builder(batch_size=4, dev="cpu")
    else:
        text = builder(batch_size=4, dev="cpu", nsample=8)
    tr = NetTrainer()
    tr.set_params(_global_cfg(text))
    tr.init_model()
    shapes = tr.net.node_shapes
    assert all(s is not None for s in shapes)
    # output layer is softmax over the right class count
    out = shapes[tr.net.out_node_index()]
    expect = {"mnist_mlp": 10, "mnist_conv": 10, "alexnet": 1000,
              "googlenet": 1000, "vgg16": 1000, "vgg19": 1000,
              "kaggle_bowl": 121,
              "transformer": 10, "transformer_lm": 256,
              "resnet50": 1000, "resnet101": 1000,
              "resnet152": 1000}[name]
    assert out[-1] == expect
    if name in ("resnet101", "resnet152", "vgg19"):
        # depth variants really are deeper than their base model
        base = {"resnet101": "resnet50", "resnet152": "resnet50",
                "vgg19": "vgg16"}[name]
        base_text = MODEL_BUILDERS[base](batch_size=4, dev="cpu",
                                         nsample=8)
        assert text.count("= conv:") > base_text.count("= conv:")


def test_resnet50_structure():
    """Bottleneck plan matches He et al. table 1: stage widths
    256/512/1024/2048, spatial 56/28/14/7 at 224px, ~25.5M params."""
    text = MODEL_BUILDERS["resnet50"](batch_size=2, dev="cpu", nsample=4,
                                      input_size=224)
    tr = NetTrainer()
    tr.set_params(_global_cfg(text))
    tr.init_model()
    g = tr.graph
    shapes = {g.node_names[i]: s for i, s in enumerate(tr.net.node_shapes)
              if s is not None and i < len(g.node_names)}
    assert shapes["s0b2"][1:] == (56, 56, 256)
    assert shapes["s1b3"][1:] == (28, 28, 512)
    assert shapes["s2b5"][1:] == (14, 14, 1024)
    assert shapes["s3b2"][1:] == (7, 7, 2048)
    total = sum(
        int(np.prod(np.shape(w)))
        for tags in tr.params.values() for w in tags.values()
    )
    assert 25e6 < total < 26e6, f"param count {total/1e6:.1f}M"


def test_googlenet_channel_plan():
    """Inception concat widths match Szegedy et al. table 1."""
    text = MODEL_BUILDERS["googlenet"](batch_size=2, dev="cpu", nsample=4)
    tr = NetTrainer()
    tr.set_params(_global_cfg(text))
    tr.init_model()
    g = tr.graph
    shapes = tr.net.node_shapes
    want = {"i3a": 256, "i3b": 480, "i4a": 512, "i4b": 512, "i4c": 512,
            "i4d": 528, "i4e": 832, "i5a": 832, "i5b": 1024}
    for node, ch in want.items():
        s = shapes[g.node_index_of(node)]
        assert s[-1] == ch, f"{node}: {s} want C={ch}"


@pytest.mark.parametrize("name", ["mnist_conv", "kaggle_bowl"])
def test_model_train_step(name):
    """One real fused train step on a small model."""
    text = MODEL_BUILDERS[name](batch_size=4, dev="cpu")
    tr = NetTrainer()
    tr.set_params(_global_cfg(text))
    tr.init_model()
    c, h, w = tr.graph.input_shape
    shape = (4, w) if c == 1 and h == 1 else (4, h, w, c)
    rng = np.random.RandomState(0)
    data = rng.randn(*shape).astype(np.float32)
    nclass = 10 if name == "mnist_conv" else 121
    labels = rng.randint(0, nclass, size=(4, 1)).astype(np.float32)
    before = {k: {t: np.asarray(v) for t, v in tags.items()}
              for k, tags in tr.params.items()}
    tr.update_all(data, labels)
    changed = any(
        not np.allclose(before[k][t], np.asarray(tr.params[k][t]))
        for k in before for t in before[k]
    )
    assert changed, "parameters did not move after a train step"


def test_googlenet_train_step_small():
    """GoogLeNet at 64px input: fused step compiles and runs on CPU."""
    text = MODEL_BUILDERS["googlenet"](
        batch_size=2, dev="cpu", input_size=64, nsample=4
    )
    tr = NetTrainer()
    tr.set_params(_global_cfg(text))
    tr.init_model()
    rng = np.random.RandomState(0)
    data = rng.randn(2, 64, 64, 3).astype(np.float32)
    labels = rng.randint(0, 1000, size=(2, 1)).astype(np.float32)
    tr.update_all(data, labels)
    assert tr.epoch_counter == 1


def test_googlenet_fuse_1x1_prediction_parity():
    """fuse_1x1 finds 9 groups of 3 on the real GoogLeNet graph and the
    fused forward matches the plain one on identical weights."""
    text = MODEL_BUILDERS["googlenet"](
        batch_size=2, dev="cpu", input_size=64, nsample=4
    )
    rng = np.random.RandomState(1)
    data = rng.randn(2, 64, 64, 3).astype(np.float32)

    def build(fuse):
        tr = NetTrainer()
        tr.set_params(_global_cfg(text + f"fuse_1x1 = {fuse}\n"))
        tr.set_param("seed", "3")
        tr.init_model()
        return tr

    t0, t1 = build(0), build(1)
    groups, member = t1.net._sibling_1x1_groups()
    assert sorted(len(v) for v in groups.values()) == [3] * 9
    from cxxnet_tpu.io.data import DataBatch
    b = DataBatch(data=data, label=None)
    p0 = t0.extract_feature(b, "top[-1]")
    p1 = t1.extract_feature(b, "top[-1]")
    np.testing.assert_allclose(np.asarray(p0), np.asarray(p1),
                               rtol=2e-4, atol=2e-5)
