"""Test harness configuration.

Tests run on CPU with 8 virtual XLA devices so the multi-chip sharding paths
(data parallelism over a ``jax.sharding.Mesh``) can be exercised without TPU
hardware.  Must be set before jax is imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.RandomState(42)
