"""Test harness configuration.

Tests run on CPU with 8 virtual XLA devices so the multi-chip sharding paths
(data parallelism over a ``jax.sharding.Mesh``) can be exercised without TPU
hardware.  Must be set before jax is imported anywhere.
"""

import os

# Force CPU: the container exports JAX_PLATFORMS=axon (the real TPU tunnel),
# which must never be used for tests — it is single-client and slow to
# compile. setdefault would keep the axon value; tests hard-override.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# The container's sitecustomize (PYTHONPATH=/root/.axon_site) registers the
# 'axon' TPU-tunnel PJRT plugin in every interpreter; initializing it from
# tests would contend for (or hang on) the single-client relay.  Deregister
# the factory before any backend is initialized so tests are pure-CPU.
import jax  # noqa: E402

try:
    from jax._src import xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
except Exception:  # pragma: no cover - jax internals moved; cpu-forcing env remains
    pass
# sitecustomize's register() overrides jax_platforms to "axon,cpu" via
# jax.config, which wins over the env var — force it back.
jax.config.update("jax_platforms", "cpu")


@pytest.fixture
def rng():
    return np.random.RandomState(42)


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: multi-process / long-running tests"
    )
    config.addinivalue_line(
        "markers", "chaos: fault-injection chaos suite (tools/chaos_run.sh)"
    )


@pytest.fixture(autouse=True)
def _disarm_faults():
    """No test leaks an armed fault spec (or a thread blocked at a hang
    site) into the next one: reset() also releases in-progress hangs."""
    yield
    from cxxnet_tpu.utils import faults

    faults.reset()


# ----------------------------------------------------------------------
# daemon-thread leak accounting.  The multi-file tier-1 flake (see
# CHANGES.md, PR 7) had ~24 leaked daemon threads alive at crash time;
# this guard bounds that suspect: every module gets a grace period to
# join the threads it started, the survivors are accounted, and a module
# that leaks more than CXXNET_THREAD_LEAK_LIMIT (default 12) fails
# loudly with their names instead of letting the leak compound silently
# across the suite.
_THREAD_LEAKS = {}  # module name -> [thread names] (session accounting)


@pytest.fixture(autouse=True, scope="module")
def _thread_leak_guard(request):
    import threading
    import time

    # object identity, not ident: thread idents are recycled by the OS,
    # so an ident set would mistake a fresh thread for a finished one
    before = set(threading.enumerate())
    yield
    deadline = time.monotonic() + 3.0

    def _leaked():
        return [
            t for t in threading.enumerate()
            if t.is_alive() and t not in before
            and t is not threading.current_thread()
        ]

    new = _leaked()
    while new and time.monotonic() < deadline:
        for t in new:  # join what exits on its own (close() in flight)
            t.join(timeout=0.2)
        new = _leaked()
    if not new:
        return
    names = sorted(t.name for t in new)
    _THREAD_LEAKS[request.module.__name__] = names
    limit = int(os.environ.get("CXXNET_THREAD_LEAK_LIMIT", "12"))
    if len(new) > limit:
        pytest.fail(
            f"{request.module.__name__} leaked {len(new)} daemon "
            f"threads (> limit {limit}): {names} — close your "
            "iterators/engines/evaluators (CXXNET_THREAD_LEAK_LIMIT "
            "overrides)", pytrace=False,
        )


def pytest_terminal_summary(terminalreporter):
    if _THREAD_LEAKS:
        total = sum(len(v) for v in _THREAD_LEAKS.values())
        terminalreporter.write_sep(
            "-", f"daemon-thread leak accounting: {total} leaked")
        for mod, names in sorted(_THREAD_LEAKS.items()):
            terminalreporter.write_line(f"  {mod}: {len(names)} {names}")


def run_cli(args, cwd, timeout=300, module=True):
    """Shared subprocess harness for driving the CLI (or a tool script,
    module=False with args[0] an absolute script path) in tests.

    The env override is load-bearing: PYTHONPATH=REPO drops
    /root/.axon_site, whose sitecustomize would otherwise dial the
    fragile single-client axon TPU relay from every test subprocess.
    """
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo
    cmd = ([sys.executable, "-m", "cxxnet_tpu", *args] if module
           else [sys.executable, *args])
    return subprocess.run(
        cmd, capture_output=True, text=True, cwd=cwd, env=env,
        timeout=timeout,
    )
