"""Multi-process distributed training (SURVEY §4.4: multi-node without a
cluster).

The reference rehearses its distributed protocol by running multiple
worker/server *processes* on one machine (``example/MNIST/mpi.conf``); the
TPU-native analog is a 2-process ``jax.distributed`` job over CPU devices.
Each process feeds different local data; after training, weights must be
identical on every process (the ``test_on_server=1`` / ``CheckWeight_``
discipline, ``async_updater-inl.hpp:148-153``).
"""

import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from cxxnet_tpu.parallel.distributed import distributed_spec

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent(
    """
    import os, sys
    import numpy as np
    rank = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]
    out_dir = sys.argv[4]
    os.environ["CXN_COORDINATOR"] = f"localhost:{port}"
    os.environ["CXN_NUM_PROC"] = str(nproc)
    os.environ["CXN_PROC_ID"] = str(rank)
    from cxxnet_tpu.parallel import maybe_init_distributed
    assert maybe_init_distributed([])
    import jax
    assert jax.process_count() == nproc
    ndev = len(jax.devices())
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.io.data import DataBatch
    cfg = [("dev", f"cpu:0-{ndev-1}"), ("batch_size", "16"),
           ("input_shape", "1,1,10"), ("seed", "7"), ("eta", "0.1"),
           ("momentum", "0.9"),
           ("netconfig", "start"), ("layer[0->1]", "fullc:fc1"),
           ("nhidden", "8"), ("layer[1->2]", "softmax"),
           ("netconfig", "end")]
    tr = NetTrainer(); tr.set_params(cfg); tr.init_model()
    rng = np.random.RandomState(100 + rank)  # different data per process
    for step in range(3):
        x = rng.randn(16 // nproc, 10).astype(np.float32)
        y = rng.randint(0, 8, size=(16 // nproc, 1)).astype(np.float32)
        tr.update(DataBatch(data=x, label=y))
    assert tr.epoch_counter == 3
    np.save(os.path.join(out_dir, f"w{rank}.npy"),
            np.asarray(tr.params["l0_fc1"]["wmat"]))
    # test_on_server discipline: replicated weights identical everywhere
    assert tr.check_weight_sync() == 0.0
    # ... and the check actually DETECTS divergence: perturb one rank's
    # local replica and expect the RuntimeError on every process
    if rank == 1:
        # (eager math on a cross-process global array is not allowed;
        # rebuild the leaf as a process-local array instead)
        w = tr.params["l0_fc1"]["wmat"]
        tr.params["l0_fc1"]["wmat"] = jax.device_put(
            np.asarray(w.addressable_shards[0].data) + 1.0)
    try:
        tr.check_weight_sync()
        raise SystemExit("divergence not detected")
    except RuntimeError:
        pass
    """
)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_distributed_spec_parsing():
    assert distributed_spec([]) is None or "CXN_COORDINATOR" in os.environ
    spec = distributed_spec(
        [("dist_coordinator", "h:1"), ("dist_num_proc", "4"),
         ("dist_proc_id", "2")]
    )
    assert spec == ("h:1", 4, 2)
    with pytest.raises(ValueError):
        distributed_spec([("dist_coordinator", "h:1")])


@pytest.mark.slow
def _run_workers(script_text, tmp_path, nproc, ndev, extra_args=(),
                 timeout=240):
    """Launch nproc copies of a worker script over a fresh coordinator
    port (ndev CPU devices each), assert success, return stdouts."""
    script = tmp_path / "worker.py"
    script.write_text(script_text)
    port = _free_port()
    env = {
        **os.environ,
        "PYTHONPATH": REPO,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": f"--xla_force_host_platform_device_count={ndev}",
    }
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(r), str(nproc), str(port)]
            + [str(a) for a in extra_args],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        for r in range(nproc)
    ]
    try:
        outs = [p.communicate(timeout=timeout)[0] for p in procs]
    finally:
        for p in procs:  # bound the damage when a rank hangs/fails
            if p.poll() is None:
                p.kill()
    for p, o in zip(procs, outs):
        assert p.returncode == 0, o.decode()
    return outs


@pytest.mark.parametrize("nproc,ndev", [
    # the 2x2 row is a true data x model mesh SPANNING processes: it
    # needs cross-process CPU collectives (gloo), which
    # maybe_init_distributed now arms before backend init
    pytest.param(2, 2),
    pytest.param(4, 1, marks=pytest.mark.slow),
])
def test_training_weights_identical_across_processes(tmp_path, nproc, ndev):
    """nproc procs x ndev CPU devices, different local data per
    process: weights bit-identical everywhere after training, and
    check_weight_sync detects a single diverged rank.  The 4-process
    row exercises the protocol beyond the pairwise case (VERDICT r4
    #7)."""
    _run_workers(WORKER, tmp_path, nproc, ndev, extra_args=[tmp_path])
    ws = [np.load(tmp_path / f"w{r}.npy") for r in range(nproc)]
    for r in range(1, nproc):
        np.testing.assert_allclose(ws[0], ws[r], rtol=0, atol=0)
    # and training actually moved the weights
    assert np.abs(ws[0]).max() > 0


def _run_cli_dist(tmp_path, conf, port, nproc=2, ndev=2, timeout=300,
                  ret_outs=False):
    """Launch nproc CLI processes on one conf (the dist.conf procedure)
    and return their per-rank working dirs after asserting success."""
    env = {
        **os.environ,
        "PYTHONPATH": REPO,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": f"--xla_force_host_platform_device_count={ndev}",
    }
    procs, dirs = [], []
    for r in range(nproc):
        d = tmp_path / f"p{r}"
        d.mkdir()
        dirs.append(d)
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "cxxnet_tpu", str(conf),
             f"dist_coordinator=localhost:{port}", f"dist_proc_id={r}"],
            env=env, cwd=str(d),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        ))
    try:
        outs = [p.communicate(timeout=timeout)[0] for p in procs]
    finally:
        for p in procs:  # bound the damage when a rank hangs/fails
            if p.poll() is None:
                p.kill()
    for p, o in zip(procs, outs):
        assert p.returncode == 0, o.decode()
    return outs if ret_outs else dirs


@pytest.mark.slow
def test_two_process_cli_dist_conf(tmp_path):
    """The dist.conf launch procedure end-to-end: 2 CLI processes share
    one conf with a GLOBAL batch_size; the driver shards the mnist
    iterator (disjoint rows) and shrinks each process's local batch, and
    both processes save identical checkpoints."""
    from cxxnet_tpu.io.mnist import write_idx_images, write_idx_labels

    rng = np.random.RandomState(0)
    imgs = rng.randint(0, 256, (128, 4, 4)).astype(np.uint8)
    labels = (imgs.reshape(128, -1).mean(1) > 127).astype(np.uint8)
    write_idx_images(str(tmp_path / "img.idx"), imgs)
    write_idx_labels(str(tmp_path / "lab.idx"), labels)
    port = _free_port()
    conf = tmp_path / "dist.conf"
    conf.write_text(f"""
dist_num_proc = 2
data = train
iter = mnist
  path_img = "{tmp_path}/img.idx"
  path_label = "{tmp_path}/lab.idx"
  shuffle = 1
iter = end
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 8
  init_sigma = 0.1
layer[fc1->out] = fullc:fc2
  nhidden = 2
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,16
batch_size = 32
dev = cpu
num_round = 2
eval_train = 0
scan_steps = 4
eta = 0.1
metric = error
silent = 1
test_on_server = 1
""")
    # scan_steps + eval_train=0: the CLI's ASYNC overlapped chunk path
    # (check_steps=False, double buffer) must not deadlock across
    # processes and must keep weights replicated; test_on_server makes
    # the CLI itself assert replication every round
    outs = _run_cli_dist(tmp_path, conf, port, ret_outs=True)
    # rank-0-writes discipline: the primary saved every round (the
    # serialize itself is collective — both ranks assembled the blob),
    # the peer wrote nothing
    m0 = tmp_path / "p0" / "models" / "0002.model"
    assert m0.is_file() and m0.stat().st_size > 0
    assert not (tmp_path / "p1" / "models").exists()
    # ...and the weights those checkpoints came from were bit-identical
    # on every process, every round (the in-run CheckWeight_ analog)
    for o in outs:
        assert o.count(b"weight-sync:max_dev=0 ok") == 2, o.decode()


@pytest.mark.slow
def test_two_process_cli_lm_dist_conf(tmp_path):
    """example/lm/dist.conf's procedure: 2 CLI processes train the byte
    LM with the text iterator sharding windows by rank, FSDP (zero=3)
    param sharding, and per-position labels — identical checkpoints on
    both processes."""
    (tmp_path / "corpus.txt").write_bytes(
        ("the quick brown fox jumps over the lazy dog. " * 80).encode()
    )
    port = _free_port()
    conf = tmp_path / "lm_dist.conf"
    conf.write_text(f"""
dist_num_proc = 2
zero = 3
data = train
iter = text
  filename = "{tmp_path}/corpus.txt"
  seq_len = 16
  shuffle = 1
iter = end
netconfig = start
layer[0->emb] = embedding:embed
  nvocab = 256
  nhidden = 32
  pos = learned
  init_sigma = 0.02
layer[emb->a] = attention:attn
  nhead = 2
  causal = 1
  init_sigma = 0.02
layer[emb,a->r] = eltwise_sum
layer[r->nf] = layer_norm:ln_f
layer[nf->logits] = fullc:lm_head
  nhidden = 256
  init_sigma = 0.02
layer[logits->logits] = softmax
  grad_scale = 0.0625
netconfig = end
input_shape = 1,1,16
label_width = 16
label_vec[0,16) = label
batch_size = 16
dev = cpu
num_round = 2
updater = adam
eta = 0.003
wd = 0.0
eval_train = 0
metric = error
silent = 1
""")
    _run_cli_dist(tmp_path, conf, port)
    # rank-0-writes discipline (see test_two_process_cli_dist_conf);
    # checkpoint assembly is COLLECTIVE — the FSDP (zero=3) param
    # shards allgather on both ranks — so a valid round-2 checkpoint on
    # the primary proves the sharded LM trained end to end without
    # deadlock and the gathered state passed CRC validation
    m0 = tmp_path / "p0" / "models" / "0002.model"
    assert m0.is_file() and m0.stat().st_size > 0
    assert not (tmp_path / "p1" / "models").exists()
    from cxxnet_tpu.utils import checkpoint as ckpt

    assert ckpt.validate_checkpoint(str(m0)) is None


BITWISE_WORKER = textwrap.dedent(
    """
    import os, sys, zlib
    import numpy as np
    rank = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]
    out_dir = sys.argv[4]
    # BOTH sides of the parity pair initialize jax.distributed (the
    # single-process run with num_processes=1): the collectives
    # implementation (gloo) must match for the all-reduce order — and
    # therefore the weight bits — to match across process layouts
    os.environ["CXN_COORDINATOR"] = f"localhost:{port}"
    os.environ["CXN_NUM_PROC"] = str(nproc)
    os.environ["CXN_PROC_ID"] = str(rank)
    from cxxnet_tpu.parallel import maybe_init_distributed
    assert maybe_init_distributed([])
    import jax
    assert len(jax.devices()) == 4  # the same 4-device mesh either way
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.io.data import DataBatch
    cfg = [("dev", "cpu" if nproc > 1 else "cpu:0-3"),
           ("batch_size", "16"),
           ("input_shape", "1,1,10"), ("seed", "7"), ("eta", "0.1"),
           ("momentum", "0.9"), ("eval_train", "0"),
           ("shard_weight_update", "1"),
           ("netconfig", "start"), ("layer[0->1]", "fullc:fc1"),
           ("nhidden", "8"), ("layer[1->2]", "softmax"),
           ("netconfig", "end")]
    tr = NetTrainer(); tr.set_params(cfg); tr.init_model()
    # the SAME global stream everywhere; each rank feeds its CONTIGUOUS
    # slice (matching make_array assembly order — the dist_shard=block
    # iterator contract)
    rng = np.random.RandomState(5)
    for step in range(6):
        gx = rng.randn(16, 10).astype(np.float32)
        gy = rng.randint(0, 8, (16, 1)).astype(np.float32)
        lo, hi = rank * (16 // nproc), (rank + 1) * (16 // nproc)
        tr.update(DataBatch(data=gx[lo:hi], label=gy[lo:hi]))
    crc = zlib.crc32(tr.checkpoint_bytes())
    with open(os.path.join(out_dir, f"bw_{nproc}_{rank}.txt"), "w") as f:
        f.write(f"{crc:#010x}")
    """
)


@pytest.mark.slow
def test_four_process_mesh_bitwise_equals_single_process(tmp_path):
    """ROADMAP item 1 acceptance: the 4-process CPU-mesh trainer is
    BITWISE identical (equal checkpoint CRCs) to the single-process
    trainer over the same 4-device mesh — one SPMD program, one
    collectives implementation, one reduction order, zero drift."""
    _run_workers(BITWISE_WORKER, tmp_path, 4, 1, extra_args=[tmp_path])
    crcs = {(tmp_path / f"bw_4_{r}.txt").read_text() for r in range(4)}
    assert len(crcs) == 1, f"ranks disagree: {crcs}"
    _run_workers(BITWISE_WORKER, tmp_path, 1, 4, extra_args=[tmp_path])
    single = (tmp_path / "bw_1_0.txt").read_text()
    assert crcs == {single}, (
        f"4-process CRC {crcs} != single-process CRC {single}"
    )


SCAN_WORKER = textwrap.dedent(
    """
    import os, sys
    import numpy as np
    rank = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]
    out_dir = sys.argv[4]
    if nproc > 1:
        os.environ["CXN_COORDINATOR"] = f"localhost:{port}"
        os.environ["CXN_NUM_PROC"] = str(nproc)
        os.environ["CXN_PROC_ID"] = str(rank)
        from cxxnet_tpu.parallel import maybe_init_distributed
        assert maybe_init_distributed([])
    import jax
    ndev = len(jax.devices())
    from cxxnet_tpu.nnet.trainer import NetTrainer
    cfg = [("dev", f"cpu:0-{ndev-1}" if nproc == 1 else "cpu"),
           ("batch_size", "16"),
           ("input_shape", "1,1,10"), ("seed", "7"), ("eta", "0.1"),
           ("momentum", "0.9"), ("eval_train", "1"), ("metric", "error"),
           ("netconfig", "start"), ("layer[0->1]", "fullc:fc1"),
           ("nhidden", "8"), ("layer[1->2]", "softmax"),
           ("netconfig", "end")]
    tr = NetTrainer(); tr.set_params(cfg); tr.init_model()
    # the SAME global [K, 16, 10] step-stack on every process; each rank
    # slices its own batch rows, matching make_array assembly order
    rng = np.random.RandomState(5)
    K = 4
    gx = rng.randn(K, 16, 10).astype(np.float32)
    gy = rng.randint(0, 8, size=(K, 16, 1)).astype(np.float32)
    lo, hi = rank * (16 // nproc), (rank + 1) * (16 // nproc)
    losses = tr.update_scan(gx[:, lo:hi], gy[:, lo:hi])
    assert tr.epoch_counter == K
    line = tr.evaluate(None, "train")
    np.save(os.path.join(out_dir, f"scan_w{rank}.npy"),
            np.asarray(tr.params["l0_fc1"]["wmat"]))
    np.save(os.path.join(out_dir, f"scan_l{rank}.npy"), losses)
    with open(os.path.join(out_dir, f"scan_m{rank}.txt"), "w") as f:
        f.write(line)
    """
)


@pytest.mark.slow
def test_two_process_update_scan_matches_single(tmp_path):
    """The device-side multi-step scan path under jax.distributed: same
    weights, losses and (reduced) train metric as one process running
    the identical global step-stack (VERDICT r2 #4)."""
    script = tmp_path / "scan_worker.py"
    script.write_text(SCAN_WORKER)
    port = _free_port()

    def run(nproc, ndev_per_proc):
        env = {
            **os.environ,
            "PYTHONPATH": REPO,
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS":
                f"--xla_force_host_platform_device_count={ndev_per_proc}",
        }
        procs = [
            subprocess.Popen(
                [sys.executable, str(script), str(r), str(nproc), str(port),
                 str(tmp_path)],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            )
            for r in range(nproc)
        ]
        outs = [p.communicate(timeout=240)[0] for p in procs]
        for p, o in zip(procs, outs):
            assert p.returncode == 0, o.decode()

    run(2, 2)  # 2 procs x 2 devices
    w0 = np.load(tmp_path / "scan_w0.npy")
    w1 = np.load(tmp_path / "scan_w1.npy")
    np.testing.assert_array_equal(w0, w1)
    m0 = (tmp_path / "scan_m0.txt").read_text()
    m1 = (tmp_path / "scan_m1.txt").read_text()
    assert m0 == m1 and "train-error" in m0

    run(1, 4)  # single process, same 4-device mesh, same global stack
    ws = np.load(tmp_path / "scan_w0.npy")
    np.testing.assert_allclose(w0, ws, rtol=0, atol=1e-6)
    ls = np.load(tmp_path / "scan_l0.npy")
    l0 = np.load(tmp_path / "scan_l1.npy")  # from the 2-proc run (rank 1)
    np.testing.assert_allclose(l0, ls, rtol=0, atol=1e-6)
    ms = (tmp_path / "scan_m0.txt").read_text()
    assert ms == m0  # reduced 2-proc metric == single-process metric


def _eval_conf(tmp_path, nproc_line):
    return f"""
{nproc_line}
data = train
iter = mnist
  path_img = "{tmp_path}/img.idx"
  path_label = "{tmp_path}/lab.idx"
iter = end
eval = test
iter = mnist
  path_img = "{tmp_path}/img.idx"
  path_label = "{tmp_path}/lab.idx"
iter = end
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 8
  init_sigma = 0.1
layer[fc1->out] = fullc:fc2
  nhidden = 2
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,16
batch_size = 32
dev = cpu
num_round = 1
eval_train = 0
eta = 0.0
wd = 0.0
momentum = 0.0
seed = 3
metric = error
metric = logloss
silent = 1
save_model = 0
"""


@pytest.mark.slow
def test_two_process_sharded_eval_matches_single(tmp_path):
    """Eval iterators shard per process and the metric counters reduce
    across the job: with frozen weights (eta=0) the 2-process eval line
    equals the single-process one exactly (VERDICT r2 #4 / weak #3)."""
    import re

    from cxxnet_tpu.io.mnist import write_idx_images, write_idx_labels

    rng = np.random.RandomState(0)
    imgs = rng.randint(0, 256, (128, 4, 4)).astype(np.uint8)
    labels = (imgs.reshape(128, -1).mean(1) > 127).astype(np.uint8)
    write_idx_images(str(tmp_path / "img.idx"), imgs)
    write_idx_labels(str(tmp_path / "lab.idx"), labels)

    def eval_line(out: bytes) -> str:
        m = re.search(r"\[1\]\t(\S.*)", out.decode())
        assert m, out.decode()
        return m.group(1)

    # single process
    conf1 = tmp_path / "eval1.conf"
    conf1.write_text(_eval_conf(tmp_path, ""))
    env = {
        **os.environ, "PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
    }
    d1 = tmp_path / "single"
    d1.mkdir()
    r = subprocess.run(
        [sys.executable, "-m", "cxxnet_tpu", str(conf1)],
        env=env, cwd=str(d1), capture_output=True, timeout=240,
    )
    assert r.returncode == 0, r.stderr.decode()
    single = eval_line(r.stderr)

    # two processes, sharded eval + cross-process reduction
    conf2 = tmp_path / "eval2.conf"
    conf2.write_text(_eval_conf(tmp_path, "dist_num_proc = 2"))
    port = _free_port()
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    procs, outs = [], []
    for rank in range(2):
        d = tmp_path / f"e{rank}"
        d.mkdir()
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "cxxnet_tpu", str(conf2),
             f"dist_coordinator=localhost:{port}", f"dist_proc_id={rank}"],
            env=env, cwd=str(d),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        ))
    try:
        outs = [p.communicate(timeout=240) for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, (so, se) in zip(procs, outs):
        assert p.returncode == 0, (so + se).decode()
    lines = [eval_line(se) for _, se in outs]
    assert lines[0] == lines[1] == single, (lines, single)


WORKER_SHARDED = textwrap.dedent(
    """
    import os, sys
    import numpy as np
    rank = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]
    os.environ["CXN_COORDINATOR"] = f"localhost:{port}"
    os.environ["CXN_NUM_PROC"] = str(nproc)
    os.environ["CXN_PROC_ID"] = str(rank)
    from cxxnet_tpu.parallel import maybe_init_distributed
    assert maybe_init_distributed([])
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.io.data import DataBatch
    ndev = len(jax.local_devices())
    cfg = [("dev", f"cpu:0-{nproc*ndev-1}"), ("batch_size", "16"),
           ("input_shape", "1,1,10"), ("seed", "7"), ("eta", "0.1"),
           ("model_parallel", "2"),
           ("netconfig", "start"), ("layer[0->1]", "fullc:fc1"),
           ("nhidden", "8"), ("layer[1->2]", "softmax"),
           ("netconfig", "end")]
    tr = NetTrainer(); tr.set_params(cfg); tr.init_model()
    rng = np.random.RandomState(100 + rank)
    x = rng.randn(16 // nproc, 10).astype(np.float32)
    y = rng.randint(0, 8, size=(16 // nproc, 1)).astype(np.float32)
    tr.update(DataBatch(data=x, label=y))
    sharded = [l for l in jax.tree_util.tree_leaves(tr.params)
               if not l.sharding.is_fully_replicated]
    assert sharded, "expected TP-sharded leaves in this config"
    # healthy: every replica of every logical slice agrees, everywhere
    assert tr.check_weight_sync() == 0.0
    # corrupt rank 1's local replica of ONE model-axis shard; the
    # allgathered slice-keyed fingerprints must diverge on EVERY process
    mesh = tr.mesh_plan.mesh
    sh = NamedSharding(mesh, P("model", None))
    shape = (8, 4)
    base = np.arange(32, dtype=np.float32).reshape(shape)
    bufs = []
    items = sorted(
        ((d, idx) for d, idx in sh.devices_indices_map(shape).items()
         if d.process_index == jax.process_index()),
        key=lambda kv: kv[0].id,
    )
    for k, (d, idx) in enumerate(items):
        local = base[idx].copy()
        if rank == 1 and k == 0:
            local[0, 0] += 0.5
        bufs.append(jax.device_put(local, d))
    tr.params["zz_corrupt"] = {
        "wmat": jax.make_array_from_single_device_arrays(shape, sh, bufs)
    }
    try:
        tr.check_weight_sync()
        raise SystemExit("sharded divergence not detected")
    except RuntimeError as e:
        assert "sharded weights have diverged" in str(e), str(e)
    print("rank", rank, "ok")
    """
)


@pytest.mark.slow
@pytest.mark.parametrize("nproc,ndev", [(2, 2), (4, 1)])
def test_sharded_weight_sync_across_processes(tmp_path, nproc, ndev):
    """The cross-process branch of the shard-granular sync check: a
    2x2 (data x model) mesh over nproc processes puts replicas of the
    same TP shard on DIFFERENT processes (at 4 processes every replica
    pair spans two); the check passes healthy and detects a single
    corrupted remote replica on every rank (VERDICT r4 #7)."""
    outs = _run_workers(WORKER_SHARDED, tmp_path, nproc, ndev)
    for o in outs:
        assert b"ok" in o
