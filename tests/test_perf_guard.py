"""perf_guard sentinel tests (tools/perf_guard.py).

Pure-logic coverage of the rolling baseline, noise band, orientation
rules, history round-trip and verdict schema; the OBS=1 lane runs the
real two-measurement ``--smoke`` end to end.
"""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

import perf_guard  # noqa: E402


def _entry(ts, **metrics):
    return {"ts": ts, "bench": "io_bench", "host": "t",
            "metrics": metrics}


# ----------------------------------------------------------------------
def test_orientation_rules():
    assert not perf_guard.lower_is_better("serial.img_per_sec")
    assert not perf_guard.lower_is_better("closed.speedup")
    assert perf_guard.lower_is_better("closed.concurrent.latency_ms.p99")
    assert perf_guard.lower_is_better("closed.sequential.wall_sec")
    # markers match the FULL dotted path: a markerless leaf under a
    # latency parent must not invert the regression direction
    assert perf_guard.lower_is_better("closed.concurrent.latency_ms.mean")


def test_first_run_is_baseline_verdict():
    doc = perf_guard.compare("io_bench", {"serial.img_per_sec": 100.0},
                             history=[])
    assert doc["verdict"] == "baseline"
    assert doc["baseline"] is None
    assert perf_guard.validate_verdict(doc) == []


def test_regression_detected_outside_band():
    hist = [_entry(i, **{"serial.img_per_sec": v})
            for i, v in enumerate([100, 98, 102, 101, 99])]
    ok = perf_guard.compare("io_bench", {"serial.img_per_sec": 85.0},
                            hist, band=0.2)
    assert ok["verdict"] == "ok"  # -15% sits inside the 20% band
    bad = perf_guard.compare("io_bench", {"serial.img_per_sec": 70.0},
                             hist, band=0.2)
    assert bad["verdict"] == "regression"
    (row,) = bad["regressions"]
    assert row["metric"] == "serial.img_per_sec"
    assert row["baseline"] == 100  # median of the window
    assert perf_guard.validate_verdict(bad) == []


def test_latency_regresses_upward_and_improves_downward():
    hist = [_entry(i, **{"closed.concurrent.latency_ms.p99": 10.0})
            for i in range(5)]
    worse = perf_guard.compare(
        "io_bench", {"closed.concurrent.latency_ms.p99": 15.0}, hist,
        band=0.2)
    assert worse["verdict"] == "regression"
    better = perf_guard.compare(
        "io_bench", {"closed.concurrent.latency_ms.p99": 6.0}, hist,
        band=0.2)
    assert better["verdict"] == "ok"
    assert [r["metric"] for r in better["improvements"]] == [
        "closed.concurrent.latency_ms.p99"]


def test_rolling_window_median_ignores_older_entries():
    hist = ([_entry(i, **{"serial.img_per_sec": 1000.0})
             for i in range(3)]
            + [_entry(10 + i, **{"serial.img_per_sec": 100.0})
               for i in range(5)])
    doc = perf_guard.compare("io_bench", {"serial.img_per_sec": 95.0},
                             hist, window=5, band=0.2)
    assert doc["baseline"]["serial.img_per_sec"] == 100.0
    assert doc["verdict"] == "ok"


def test_new_metric_without_prior_history_is_not_a_regression():
    hist = [_entry(i, **{"serial.img_per_sec": 100.0}) for i in range(5)]
    doc = perf_guard.compare(
        "io_bench",
        {"serial.img_per_sec": 99.0, "workers=2.img_per_sec": 5.0},
        hist, band=0.2)
    assert doc["verdict"] == "ok"
    assert "workers=2.img_per_sec" not in (doc["baseline"] or {})


def test_history_roundtrip_skips_torn_and_foreign_lines(tmp_path):
    path = str(tmp_path / "hist.jsonl")
    perf_guard.append_history(path, _entry(1, **{"m": 1.0}))
    perf_guard.append_history(path, {"ts": 2, "bench": "serve_bench",
                                     "host": "t", "metrics": {"m": 9.0}})
    with open(path, "a", encoding="utf-8") as f:
        f.write('{"torn": \n')  # crash mid-append
    perf_guard.append_history(path, _entry(3, **{"m": 2.0}))
    hist = perf_guard.load_history(path, "io_bench")
    assert [e["metrics"]["m"] for e in hist] == [1.0, 2.0]
    assert [e["metrics"]["m"]
            for e in perf_guard.load_history(path, "serve_bench")] == [9.0]


def test_run_once_appends_and_emits_alert_event(tmp_path):
    path = str(tmp_path / "hist.jsonl")
    io_doc = {"results": [{"mode": "serial", "img_per_sec": 100.0,
                           "decode_augment_per_sec": 200.0, "stages": {}}]}
    first = perf_guard.run_once("io_bench", io_doc, path, 5, 0.2)
    assert first["verdict"] == "baseline"
    slow = {"results": [{"mode": "serial", "img_per_sec": 10.0,
                         "decode_augment_per_sec": 20.0, "stages": {}}]}
    second = perf_guard.run_once("io_bench", slow, path, 5, 0.2)
    assert second["verdict"] == "regression"
    assert len(perf_guard.load_history(path, "io_bench")) == 2
    from cxxnet_tpu.obs import recent

    kinds = [e["kind"] for e in recent(10)]
    assert "alert.perf_regression" in kinds


def test_flatten_serve_bench():
    doc = {"closed_loop": {
        "sequential": {"req_per_sec": 50.0, "rows_per_sec": 50.0,
                       "latency_ms": {"p50": 2.0, "p99": 5.0}},
        "concurrent": {"req_per_sec": 200.0, "rows_per_sec": 200.0,
                       "latency_ms": {"p50": 4.0, "p99": 9.0}},
        "speedup": 4.0,
    }}
    m = perf_guard.flatten_serve_bench(doc)
    assert m["closed.speedup"] == 4.0
    assert m["closed.concurrent.latency_ms.p99"] == 9.0
    assert m["closed.sequential.req_per_sec"] == 50.0


def test_flatten_async_bench():
    doc = {
        "parity": {"crc_equal": True, "sync_wall_sec": 20.0,
                   "async_wall_sec": 19.0, "rounds": 3},
        "ab": {"legs": {
            "sync": {"final_err": 0.05, "wall_sec": 8.0},
            "staleness1": {"final_err": 0.06, "wall_sec": 7.5,
                           "overlap_fraction": 0.9},
        }},
        "overlap": {"sync_step_wall_sec": 0.002,
                    "async_step_wall_sec": 0.001,
                    "overlap_fraction": 0.95, "speedup": 2.0},
    }
    m = perf_guard.flatten_async_bench(doc)
    assert m["parity.crc_equal"] == 1.0
    assert m["ab.staleness1.final_err"] == 0.06
    assert m["overlap.overlap_fraction"] == 0.95
    # orientation: errors and walls regress UP, overlap regresses DOWN
    assert perf_guard.lower_is_better("ab.sync.final_err")
    assert perf_guard.lower_is_better("overlap.async_step_wall_sec")
    assert not perf_guard.lower_is_better("overlap.overlap_fraction")
    assert not perf_guard.lower_is_better("parity.crc_equal")


def test_empty_metrics_is_an_error(tmp_path):
    with pytest.raises(ValueError):
        perf_guard.run_once("io_bench", {"results": []},
                            str(tmp_path / "h.jsonl"), 5, 0.2)


def test_verdict_schema_catches_drift():
    doc = perf_guard.compare("io_bench", {"m": 1.0}, [])
    assert perf_guard.validate_verdict(doc) == []
    bad = dict(doc)
    bad["verdict"] = "maybe"
    assert perf_guard.validate_verdict(bad)
    bad2 = dict(doc)
    bad2["metrics"] = {"m": float("nan")}
    assert perf_guard.validate_verdict(bad2)
    json.dumps(doc)  # the verdict is a printable JSON document
