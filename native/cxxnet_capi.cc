/*!
 * C ABI implementation: embedded-CPython forwarding to
 * cxxnet_tpu.capi_shim (see cxxnet_capi.h for the contract).
 *
 * Design: the reference's wrapper (cxxnet_wrapper.cc) linked the whole
 * C++ engine into the shared object; here the engine is JAX/XLA, so
 * the natural native binding is an embedded interpreter owning the
 * framework, with the C layer doing handle + buffer marshalling only.
 * Each handle owns: the Python object, plus references to the arrays /
 * strings most recently returned through it (keeps the C pointers
 * alive until the next call on the same handle — the reference's
 * temp-buffer lifetime rule).
 *
 * Threading: every entry point takes the GIL (PyGILState_Ensure), so
 * the ABI is safe to call from any host thread; calls serialize on the
 * interpreter, which matches the single-stream trainer model.
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include "cxxnet_capi.h"

#include <cstring>
#include <mutex>
#include <string>
#include <vector>

namespace {

thread_local std::string g_last_error;

struct Handle {
  PyObject *obj = nullptr;        // DataIter or Net instance
  PyObject *kept_data = nullptr;  // last data array returned
  PyObject *kept_label = nullptr; // last label/weight/pred array
  std::string kept_str;           // last evaluate() line
};

std::once_flag g_init_once;
PyObject *g_shim = nullptr;  // cxxnet_tpu.capi_shim module

void init_interpreter() {
  bool we_initialized = false;
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    we_initialized = true;
  }
  PyGILState_STATE st = PyGILState_Ensure();
  // Make the package importable relative to this shared object:
  // <repo>/native/libcxxnet_capi.so -> <repo> on sys.path.
  PyRun_SimpleString(
      "import os, sys\n"
      "try:\n"
      "    import cxxnet_tpu  # already on path\n"
      "except Exception:\n"
      "    here = os.environ.get('CXXNET_TPU_HOME')\n"
      "    if here and here not in sys.path:\n"
      "        sys.path.insert(0, here)\n");
  g_shim = PyImport_ImportModule("cxxnet_tpu.capi_shim");
  if (g_shim == nullptr) {
    PyErr_Print();
  }
  // release the GIL so host threads can enter via PyGILState_Ensure —
  // but ONLY the GIL that OUR Py_InitializeEx left held; if the host
  // process had Python running already (e.g. loaded via ctypes), the
  // GIL seen here is the caller's and must stay theirs
  PyGILState_Release(st);
  if (we_initialized && PyGILState_Check()) {
    PyEval_SaveThread();
  }
}

class Gil {
 public:
  Gil() {
    std::call_once(g_init_once, init_interpreter);
    st_ = PyGILState_Ensure();
  }
  ~Gil() { PyGILState_Release(st_); }

 private:
  PyGILState_STATE st_;
};

bool capture_error(const char *where) {
  if (!PyErr_Occurred()) return false;
  PyObject *type = nullptr, *val = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &val, &tb);
  PyObject *s = val ? PyObject_Str(val) : nullptr;
  const char *msg = s ? PyUnicode_AsUTF8(s) : nullptr;
  if (msg == nullptr) {
    PyErr_Clear();  // AsUTF8 can itself fail (e.g. lone surrogates)
    msg = "unknown python error";
  }
  g_last_error = std::string(where) + ": " + msg;
  Py_XDECREF(s);
  Py_XDECREF(type);
  Py_XDECREF(val);
  Py_XDECREF(tb);
  return true;
}

PyObject *shim_call(const char *fn, PyObject *args) {
  if (g_shim == nullptr) {
    g_last_error = "cxxnet_tpu.capi_shim failed to import (set "
                   "CXXNET_TPU_HOME or PYTHONPATH to the repo root)";
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject *f = PyObject_GetAttrString(g_shim, fn);
  if (f == nullptr) {
    capture_error(fn);
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject *r = PyObject_CallObject(f, args);
  Py_DECREF(f);
  Py_XDECREF(args);
  if (r == nullptr) capture_error(fn);
  return r;
}

// Build a numpy f32 array from a C buffer via the buffer-free path:
// shim takes (bytes, shape tuple) and np.frombuffer/reshape on its side
// would copy anyway; simplest robust marshalling is a memoryview copy.
PyObject *make_array(const float *data, const std::vector<long> &shape) {
  long n = 1;
  for (long d : shape) n *= d;
  PyObject *np = PyImport_ImportModule("numpy");
  if (np == nullptr) return nullptr;
  PyObject *bytes =
      PyBytes_FromStringAndSize(reinterpret_cast<const char *>(data),
                                n * static_cast<long>(sizeof(float)));
  PyObject *frombuffer = PyObject_GetAttrString(np, "frombuffer");
  PyObject *arr =
      PyObject_CallFunction(frombuffer, "Os", bytes, "float32");
  Py_XDECREF(frombuffer);
  Py_XDECREF(bytes);
  Py_DECREF(np);
  if (arr == nullptr) return nullptr;
  PyObject *shp = PyTuple_New(shape.size());
  for (size_t i = 0; i < shape.size(); ++i) {
    PyTuple_SET_ITEM(shp, i, PyLong_FromLong(shape[i]));
  }
  PyObject *reshaped = PyObject_CallMethod(arr, "reshape", "O", shp);
  Py_DECREF(shp);
  Py_DECREF(arr);
  return reshaped;
}

const float *array_data(PyObject *arr) {
  // C-contiguous float32 guaranteed by the shim's _c_f32
  PyObject *iface = PyObject_GetAttrString(arr, "ctypes");
  if (iface == nullptr) return nullptr;
  PyObject *ptr = PyObject_GetAttrString(iface, "data");
  Py_DECREF(iface);
  if (ptr == nullptr) return nullptr;
  const float *p =
      reinterpret_cast<const float *>(PyLong_AsUnsignedLongLong(ptr));
  Py_DECREF(ptr);
  return p;
}

bool array_shape(PyObject *arr, unsigned *out, int want_nd) {
  PyObject *shp = PyObject_GetAttrString(arr, "shape");
  if (shp == nullptr) return false;
  Py_ssize_t nd = PyTuple_Size(shp);
  for (int i = 0; i < want_nd; ++i) {
    out[i] = 1;
  }
  // right-align trailing dims (e.g. (n, d) label into oshape[2])
  for (Py_ssize_t i = 0; i < nd && i < want_nd; ++i) {
    out[i] = static_cast<unsigned>(
        PyLong_AsLong(PyTuple_GetItem(shp, i)));
  }
  Py_DECREF(shp);
  return true;
}

}  // namespace

extern "C" {

const char *CXNGetLastError(void) { return g_last_error.c_str(); }

/* ------------------------------------------------------ data iterator */
void *CXNIOCreateFromConfig(const char *cfg) {
  Gil gil;
  PyObject *r = shim_call("io_create", Py_BuildValue("(s)", cfg));
  if (r == nullptr) return nullptr;
  Handle *h = new Handle();
  h->obj = r;
  return h;
}

int CXNIONext(void *handle) {
  Gil gil;
  Handle *h = static_cast<Handle *>(handle);
  PyObject *r = shim_call("io_next", Py_BuildValue("(O)", h->obj));
  if (r == nullptr) return -1;
  int v = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return v;
}

void CXNIOBeforeFirst(void *handle) {
  Gil gil;
  Handle *h = static_cast<Handle *>(handle);
  PyObject *r = shim_call("io_before_first", Py_BuildValue("(O)", h->obj));
  Py_XDECREF(r);
}

const cxx_real_t *CXNIOGetData(void *handle, cxx_uint oshape[4],
                               cxx_uint *ostride) {
  Gil gil;
  Handle *h = static_cast<Handle *>(handle);
  PyObject *r = shim_call("io_get_data", Py_BuildValue("(O)", h->obj));
  if (r == nullptr) return nullptr;
  Py_XDECREF(h->kept_data);
  h->kept_data = r;
  array_shape(r, oshape, 4);
  if (ostride) *ostride = oshape[1] * oshape[2] * oshape[3];
  return array_data(r);
}

const cxx_real_t *CXNIOGetLabel(void *handle, cxx_uint oshape[2],
                                cxx_uint *ostride) {
  Gil gil;
  Handle *h = static_cast<Handle *>(handle);
  PyObject *r = shim_call("io_get_label", Py_BuildValue("(O)", h->obj));
  if (r == nullptr) return nullptr;
  Py_XDECREF(h->kept_label);
  h->kept_label = r;
  array_shape(r, oshape, 2);
  if (ostride) *ostride = oshape[1];
  return array_data(r);
}

void CXNIOFree(void *handle) {
  Gil gil;
  Handle *h = static_cast<Handle *>(handle);
  Py_XDECREF(h->obj);
  Py_XDECREF(h->kept_data);
  Py_XDECREF(h->kept_label);
  delete h;
}

/* -------------------------------------------------------------- net */
void *CXNNetCreate(const char *device, const char *cfg) {
  Gil gil;
  PyObject *r = shim_call(
      "net_create",
      device ? Py_BuildValue("(ss)", device, cfg)
             : Py_BuildValue("(Os)", Py_None, cfg));
  if (r == nullptr) return nullptr;
  Handle *h = new Handle();
  h->obj = r;
  return h;
}

void CXNNetFree(void *handle) { CXNIOFree(handle); }

static int void_call(const char *fn, PyObject *args) {
  PyObject *r = shim_call(fn, args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int CXNNetSetParam(void *handle, const char *name, const char *val) {
  Gil gil;
  Handle *h = static_cast<Handle *>(handle);
  return void_call("net_set_param",
                   Py_BuildValue("(Oss)", h->obj, name, val));
}

int CXNNetInitModel(void *handle) {
  Gil gil;
  Handle *h = static_cast<Handle *>(handle);
  return void_call("net_init_model", Py_BuildValue("(O)", h->obj));
}

int CXNNetSaveModel(void *handle, const char *fname) {
  Gil gil;
  Handle *h = static_cast<Handle *>(handle);
  return void_call("net_save_model", Py_BuildValue("(Os)", h->obj, fname));
}

int CXNNetLoadModel(void *handle, const char *fname) {
  Gil gil;
  Handle *h = static_cast<Handle *>(handle);
  return void_call("net_load_model", Py_BuildValue("(Os)", h->obj, fname));
}

int CXNNetStartRound(void *handle, int round) {
  Gil gil;
  Handle *h = static_cast<Handle *>(handle);
  return void_call("net_start_round", Py_BuildValue("(Oi)", h->obj, round));
}

int CXNNetUpdateBatch(void *handle, const cxx_real_t *p_data,
                      const cxx_uint dshape[4], const cxx_real_t *p_label,
                      const cxx_uint lshape[2]) {
  Gil gil;
  Handle *h = static_cast<Handle *>(handle);
  PyObject *d = make_array(
      p_data, {static_cast<long>(dshape[0]), static_cast<long>(dshape[1]),
               static_cast<long>(dshape[2]), static_cast<long>(dshape[3])});
  PyObject *l = make_array(
      p_label,
      {static_cast<long>(lshape[0]), static_cast<long>(lshape[1])});
  if (d == nullptr || l == nullptr) {
    capture_error("net_update_batch");
    Py_XDECREF(d);
    Py_XDECREF(l);
    return -1;
  }
  int rc = void_call("net_update_batch",
                     Py_BuildValue("(OOO)", h->obj, d, l));
  Py_DECREF(d);
  Py_DECREF(l);
  return rc;
}

int CXNNetUpdateIter(void *handle, void *data_handle) {
  Gil gil;
  Handle *h = static_cast<Handle *>(handle);
  Handle *it = static_cast<Handle *>(data_handle);
  return void_call("net_update_iter",
                   Py_BuildValue("(OO)", h->obj, it->obj));
}

static const cxx_real_t *keep_pred(Handle *h, PyObject *r,
                                   cxx_uint *out_size) {
  if (r == nullptr) return nullptr;
  Py_XDECREF(h->kept_label);
  h->kept_label = r;
  unsigned shp[2] = {0, 1};
  array_shape(r, shp, 1);
  if (out_size) *out_size = shp[0];
  return array_data(r);
}

const cxx_real_t *CXNNetPredictBatch(void *handle, const cxx_real_t *p_data,
                                     const cxx_uint dshape[4],
                                     cxx_uint *out_size) {
  Gil gil;
  Handle *h = static_cast<Handle *>(handle);
  PyObject *d = make_array(
      p_data, {static_cast<long>(dshape[0]), static_cast<long>(dshape[1]),
               static_cast<long>(dshape[2]), static_cast<long>(dshape[3])});
  if (d == nullptr) {
    capture_error("net_predict_batch");
    return nullptr;
  }
  PyObject *r = shim_call("net_predict_batch",
                          Py_BuildValue("(OO)", h->obj, d));
  Py_DECREF(d);
  return keep_pred(h, r, out_size);
}

const cxx_real_t *CXNNetPredictIter(void *handle, void *data_handle,
                                    cxx_uint *out_size) {
  Gil gil;
  Handle *h = static_cast<Handle *>(handle);
  Handle *it = static_cast<Handle *>(data_handle);
  PyObject *r = shim_call("net_predict_iter",
                          Py_BuildValue("(OO)", h->obj, it->obj));
  return keep_pred(h, r, out_size);
}

static const cxx_real_t *keep_2d(Handle *h, PyObject *r,
                                 cxx_uint oshape[2]) {
  if (r == nullptr) return nullptr;
  if (r == Py_None) {  // missing weight -> NULL (reference behavior)
    Py_DECREF(r);
    g_last_error = "no such weight";
    oshape[0] = oshape[1] = 0;
    return nullptr;
  }
  Py_XDECREF(h->kept_data);
  h->kept_data = r;
  array_shape(r, oshape, 2);
  return array_data(r);
}

const cxx_real_t *CXNNetExtractBatch(void *handle, const cxx_real_t *p_data,
                                     const cxx_uint dshape[4],
                                     const char *node_name,
                                     cxx_uint oshape[2]) {
  Gil gil;
  Handle *h = static_cast<Handle *>(handle);
  PyObject *d = make_array(
      p_data, {static_cast<long>(dshape[0]), static_cast<long>(dshape[1]),
               static_cast<long>(dshape[2]), static_cast<long>(dshape[3])});
  if (d == nullptr) {
    capture_error("net_extract_batch");
    return nullptr;
  }
  PyObject *r = shim_call("net_extract_batch",
                          Py_BuildValue("(OOs)", h->obj, d, node_name));
  Py_DECREF(d);
  return keep_2d(h, r, oshape);
}

const cxx_real_t *CXNNetExtractIter(void *handle, void *data_handle,
                                    const char *node_name,
                                    cxx_uint oshape[2]) {
  Gil gil;
  Handle *h = static_cast<Handle *>(handle);
  Handle *it = static_cast<Handle *>(data_handle);
  PyObject *r = shim_call(
      "net_extract_iter",
      Py_BuildValue("(OOs)", h->obj, it->obj, node_name));
  return keep_2d(h, r, oshape);
}

const char *CXNNetEvaluate(void *handle, void *data_handle,
                           const char *data_name) {
  Gil gil;
  Handle *h = static_cast<Handle *>(handle);
  Handle *it = static_cast<Handle *>(data_handle);
  PyObject *r = shim_call(
      "net_evaluate", Py_BuildValue("(OOs)", h->obj, it->obj, data_name));
  if (r == nullptr) return nullptr;
  const char *s = PyUnicode_AsUTF8(r);
  h->kept_str = s ? s : "";
  Py_DECREF(r);
  return h->kept_str.c_str();
}

int CXNNetSetWeight(void *handle, const cxx_real_t *p_weight,
                    cxx_uint size_weight, const char *layer_name,
                    const char *wtag) {
  Gil gil;
  Handle *h = static_cast<Handle *>(handle);
  PyObject *w =
      make_array(p_weight, {static_cast<long>(size_weight)});
  if (w == nullptr) {
    capture_error("net_set_weight");
    return -1;
  }
  int rc = void_call(
      "net_set_weight",
      Py_BuildValue("(OOss)", h->obj, w, layer_name, wtag));
  Py_DECREF(w);
  return rc;
}

const cxx_real_t *CXNNetGetWeight(void *handle, const char *layer_name,
                                  const char *wtag, cxx_uint oshape[2]) {
  Gil gil;
  Handle *h = static_cast<Handle *>(handle);
  PyObject *r = shim_call(
      "net_get_weight",
      Py_BuildValue("(Oss)", h->obj, layer_name, wtag));
  return keep_2d(h, r, oshape);
}

}  // extern "C"
