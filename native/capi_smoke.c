/*!
 * Smoke test of the C ABI from a pure-C host (no Python in main()):
 * builds a synthetic-data iterator and a small MLP, trains a few
 * rounds, evaluates, predicts, and round-trips a weight.  Mirrors what
 * a non-Python embedder of the reference did through
 * cxxnet_wrapper.h.  Run by tests/test_capi.py; exits non-zero on any
 * failure.
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "cxxnet_capi.h"

#define CHECK(cond)                                                  \
  do {                                                               \
    if (!(cond)) {                                                   \
      fprintf(stderr, "CHECK failed at %s:%d: %s\nlast error: %s\n", \
              __FILE__, __LINE__, #cond, CXNGetLastError());         \
      exit(1);                                                       \
    }                                                                \
  } while (0)

static const char *kIterCfg =
    "iter = synthetic\n"
    "  nsample = 64\n"
    "  input_shape = 1,1,8\n"
    "  nclass = 4\n"
    "  seed = 3\n"
    "batch_size = 16\n"
    "input_shape = 1,1,8\n";

static const char *kNetCfg =
    "netconfig = start\n"
    "layer[0->1] = fullc:fc1\n"
    "  nhidden = 32\n"
    "  init_sigma = 0.1\n"
    "layer[1->2] = relu\n"
    "layer[2->3] = fullc:fc2\n"
    "  nhidden = 4\n"
    "  init_sigma = 0.1\n"
    "layer[3->3] = softmax\n"
    "netconfig = end\n"
    "input_shape = 1,1,8\n"
    "batch_size = 16\n"
    "eta = 0.3\n"
    "momentum = 0.9\n"
    "metric = error\n";

int main(void) {
  void *it = CXNIOCreateFromConfig(kIterCfg);
  CHECK(it != NULL);

  void *net = CXNNetCreate("cpu", kNetCfg);
  CHECK(net != NULL);
  CHECK(CXNNetSetParam(net, "eval_train", "0") == 0);
  CHECK(CXNNetInitModel(net) == 0);

  /* a few training epochs straight off the iterator */
  for (int round = 0; round < 12; ++round) {
    CHECK(CXNNetStartRound(net, round) == 0);
    CXNIOBeforeFirst(it);
    int n;
    while ((n = CXNIONext(it)) == 1) {
      CHECK(CXNNetUpdateIter(net, it) == 0);
    }
    CHECK(n == 0);
  }

  /* evaluate: reference line format "\tname-metric:value" */
  const char *line = CXNNetEvaluate(net, it, "smoke");
  CHECK(line != NULL);
  CHECK(strstr(line, "smoke-error:") != NULL);
  double err = atof(strstr(line, "smoke-error:") + strlen("smoke-error:"));
  fprintf(stderr, "eval:%s -> err %.4f\n", line, err);
  CHECK(err < 0.5); /* learned something on the synthetic task */

  /* predict on the iterator's current batch buffers */
  CXNIOBeforeFirst(it);
  CHECK(CXNIONext(it) == 1);
  cxx_uint dshape[4], lshape[2], stride, nout;
  const cxx_real_t *data = CXNIOGetData(it, dshape, &stride);
  const cxx_real_t *label = CXNIOGetLabel(it, lshape, &stride);
  CHECK(data != NULL && label != NULL);
  CHECK(dshape[0] == 16 && dshape[3] == 8);
  CHECK(lshape[0] == 16);
  const cxx_real_t *pred = CXNNetPredictBatch(net, data, dshape, &nout);
  CHECK(pred != NULL && nout == 16);
  for (cxx_uint i = 0; i < nout; ++i) {
    CHECK(pred[i] >= 0.0f && pred[i] <= 3.0f);
  }

  /* batch-update path with raw buffers */
  CHECK(CXNNetUpdateBatch(net, data, dshape, label, lshape) == 0);

  /* feature extraction from a named node */
  cxx_uint eshape[2];
  const cxx_real_t *feat = CXNNetExtractBatch(net, data, dshape, "2", eshape);
  CHECK(feat != NULL && eshape[0] == 16 && eshape[1] == 32);

  /* weight round-trip through the 2-D visitor view */
  cxx_uint wshape[2];
  const cxx_real_t *w = CXNNetGetWeight(net, "fc2", "wmat", wshape);
  CHECK(w != NULL && wshape[0] == 4 && wshape[1] == 32);
  float *w2 = (float *)malloc(sizeof(float) * wshape[0] * wshape[1]);
  memcpy(w2, w, sizeof(float) * wshape[0] * wshape[1]);
  w2[0] += 1.0f;
  CHECK(CXNNetSetWeight(net, w2, wshape[0] * wshape[1], "fc2", "wmat") == 0);
  const cxx_real_t *w3 = CXNNetGetWeight(net, "fc2", "wmat", wshape);
  CHECK(w3 != NULL && w3[0] > w2[0] - 1.5f && w3[0] < w2[0] + 0.5f);
  free(w2);

  /* missing weight -> NULL (reference behavior), not a fake buffer */
  cxx_uint mshape[2];
  CHECK(CXNNetGetWeight(net, "no_such_layer", "wmat", mshape) == NULL);

  /* checkpoint round-trip */
  CHECK(CXNNetSaveModel(net, "/tmp/capi_smoke.model") == 0);
  void *net2 = CXNNetCreate("cpu", kNetCfg);
  CHECK(net2 != NULL);
  CHECK(CXNNetLoadModel(net2, "/tmp/capi_smoke.model") == 0);
  const cxx_real_t *pred2 = CXNNetPredictBatch(net2, data, dshape, &nout);
  CHECK(pred2 != NULL && nout == 16);
  CXNNetFree(net2);

  /* error path: bad layer type must fail at init with a message set
   * (config is parsed lazily, reference SetParam semantics), not crash */
  void *bad = CXNNetCreate("cpu",
                           "netconfig = start\nlayer[0->1] = nope\n"
                           "netconfig = end\ninput_shape = 1,1,8\n"
                           "batch_size = 16\n");
  CHECK(bad != NULL);
  CHECK(CXNNetInitModel(bad) != 0);
  CHECK(strlen(CXNGetLastError()) > 0);
  CXNNetFree(bad);

  CXNNetFree(net);
  CXNIOFree(it);
  fprintf(stderr, "capi_smoke: all checks passed\n");
  return 0;
}
