// cxxnet-tpu native IO: threaded binary-page reader + JPEG decode pool.
//
// TPU-native replacement for the reference's two-stage ThreadBuffer
// pipeline (/root/reference/src/io/iter_thread_imbin_x-inl.hpp: a page
// thread streaming 64MB BinaryPages + a decode thread doing JPEG->HWC,
// each a utils::ThreadBuffer double buffer).  Here the same roles are a
// bounded-queue pipeline: one reader thread (sequential page reads,
// CXBP format shared with cxxnet_tpu/io/imgbin.py) feeding N libjpeg
// decode workers whose results are re-ordered to .lst order.  The TPU
// host needs many decode threads to feed >=2000 img/s/chip (SURVEY §7
// hard part (c)); the reference's single decode thread is the analog.
//
// C ABI (ctypes-consumed by cxxnet_tpu/io/native.py):
//   cxio_open(paths, ndecode) / cxio_reset / cxio_next / cxio_kind
//   cxio_shape / cxio_size / cxio_copy / cxio_close
// Records whose blob is not JPEG are passed through undecoded (kind=0);
// the Python side decodes those with PIL.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <condition_variable>
#include <deque>
#include <map>
#include <mutex>
#include <queue>
#include <string>
#include <thread>
#include <vector>

#include <csetjmp>
#include <jpeglib.h>

namespace {

constexpr uint32_t kPageMagic = 0x43584250;  // "CXBP"
// the reference's BinaryPage: (64<<18) i32s = 64 MiB exactly (io.h:226)
constexpr size_t kRefPageBytes = (64u << 18) * 4;
constexpr size_t kInQueueCap = 512;          // encoded blobs in flight
// Sanity bounds on untrusted on-disk length fields: a 64 MB page format
// cannot legitimately exceed these; reject instead of bad_alloc-ing.
constexpr uint32_t kMaxRecordsPerPage = 1u << 24;
constexpr uint32_t kMaxRecordBytes = 1u << 30;
constexpr size_t kOutWindowCap = 256;        // decoded images buffered

struct Record {
  uint64_t seq = 0;
  std::vector<uint8_t> blob;   // encoded (or raw) bytes
  std::vector<uint8_t> pixels; // decoded HWC u8 (empty if kind==0)
  int h = 0, w = 0, c = 0;
  int kind = 0;                // 1 decoded, 0 pass-through blob
};

// ---------------------------------------------------------------------------
// libjpeg decode with longjmp error recovery (decoder.h:20-110 analog).
struct JpegErr {
  jpeg_error_mgr mgr;
  jmp_buf jb;
};

void jpeg_err_exit(j_common_ptr cinfo) {
  JpegErr* e = reinterpret_cast<JpegErr*>(cinfo->err);
  longjmp(e->jb, 1);
}

bool DecodeJpeg(const std::vector<uint8_t>& blob, Record* out) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = jpeg_err_exit;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(blob.data()), blob.size());
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  out->w = static_cast<int>(cinfo.output_width);
  out->h = static_cast<int>(cinfo.output_height);
  out->c = 3;
  out->pixels.resize(static_cast<size_t>(out->h) * out->w * 3);
  const size_t stride = static_cast<size_t>(out->w) * 3;
  while (cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW row = out->pixels.data() + cinfo.output_scanline * stride;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  out->kind = 1;
  return true;
}

bool LooksLikeJpeg(const std::vector<uint8_t>& b) {
  return b.size() > 3 && b[0] == 0xFF && b[1] == 0xD8;
}

// ---------------------------------------------------------------------------
class Pipeline {
 public:
  Pipeline(std::vector<std::string> paths, int ndecode)
      : paths_(std::move(paths)),
        ndecode_(ndecode < 1 ? 1 : ndecode) {}

  ~Pipeline() { Stop(); }

  void Start() {
    Stop();
    stop_ = false;
    reader_done_ = false;
    eof_seq_ = UINT64_MAX;
    consume_seq_ = 0;
    in_.clear();
    out_.clear();
    reader_ = std::thread([this] { ReadLoop(); });
    for (int i = 0; i < ndecode_; ++i)
      workers_.emplace_back([this] { DecodeLoop(); });
  }

  void Stop() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_in_.notify_all();
    cv_out_.notify_all();
    if (reader_.joinable()) reader_.join();
    for (auto& t : workers_)
      if (t.joinable()) t.join();
    workers_.clear();
  }

  // Blocks until the next in-order record is decoded; false at EOF.
  bool Next(Record* out) {
    std::unique_lock<std::mutex> lk(mu_);
    cv_out_.wait(lk, [this] {
      return stop_ || out_.count(consume_seq_) || consume_seq_ >= eof_seq_;
    });
    if (stop_ || consume_seq_ >= eof_seq_) return false;
    *out = std::move(out_[consume_seq_]);
    out_.erase(consume_seq_);
    ++consume_seq_;
    cv_out_.notify_all();  // window freed: wake decoders
    return true;
  }

  // Set once the reader hits a missing/corrupt shard; never cleared by
  // later records, so the consumer sees it even after draining.
  std::string Error() {
    std::lock_guard<std::mutex> lk(mu_);
    return error_;
  }

 private:
  void ReadLoop() {
    // Length fields come from untrusted on-disk pages: an exception escaping
    // a std::thread is std::terminate, so route every failure (including
    // bad_alloc from a corrupt nrec/len) into error_ for the Python side.
    try {
      ReadLoopImpl();
    } catch (const std::exception& e) {
      std::lock_guard<std::mutex> lk(mu_);
      if (error_.empty()) error_ = std::string("page reader: ") + e.what();
      eof_seq_ = consume_seq_;  // Next() returns false; consumer reads Error()
      reader_done_ = true;
      cv_in_.notify_all();
      cv_out_.notify_all();
    }
  }

  // Blocks until queue space frees; false when the pipeline is stopping.
  bool PushRecord(Record&& r) {
    std::unique_lock<std::mutex> lk(mu_);
    cv_in_.wait(lk, [this] { return stop_ || in_.size() < kInQueueCap; });
    if (stop_) return false;
    in_.push_back(std::move(r));
    cv_in_.notify_all();
    return true;
  }

  // Parse one reference-format BinaryPage (io.h:225-300): `first` is the
  // already-consumed record count (the page's leading i32), the 0 that
  // followed it was cumulative-offset[0].  Blob r spans bytes
  // [page_end - off[r+1], page_end - off[r]).  Returns 1 ok, 0 corrupt
  // (err set), -1 pipeline stopping.
  int ReadRefPage(FILE* f, uint32_t first, const std::string& path,
                  uint64_t* seq, std::string* err) {
    const uint32_t nrec = first;
    if (nrec > kMaxRecordsPerPage ||
        (static_cast<size_t>(nrec) + 2) * 4 > kRefPageBytes) {
      *err = "corrupt reference page (record count) in shard: " + path;
      return 0;
    }
    std::vector<uint8_t> page(kRefPageBytes);
    std::memcpy(page.data(), &nrec, 4);
    std::memset(page.data() + 4, 0, 4);
    if (std::fread(page.data() + 8, 1, kRefPageBytes - 8, f) !=
        kRefPageBytes - 8) {
      *err = "truncated reference page in shard: " + path;
      return 0;
    }
    std::vector<int32_t> offs(nrec + 1);
    std::memcpy(offs.data(), page.data() + 4, (nrec + 1) * 4);
    for (uint32_t r = 0; r < nrec; ++r) {
      const int64_t lo = offs[r], hi = offs[r + 1];
      if (lo < 0 || hi < lo ||
          hi + (static_cast<int64_t>(nrec) + 2) * 4 >
              static_cast<int64_t>(kRefPageBytes)) {
        *err = "corrupt reference page offsets in shard: " + path;
        return 0;
      }
      Record rec;
      rec.seq = *seq;
      rec.blob.assign(page.data() + kRefPageBytes - hi,
                      page.data() + kRefPageBytes - lo);
      if (!PushRecord(std::move(rec))) return -1;
      ++*seq;
    }
    return 1;
  }

  void ReadLoopImpl() {
    uint64_t seq = 0;
    std::string err;
    for (const auto& path : paths_) {
      FILE* f = std::fopen(path.c_str(), "rb");
      if (!f) {
        err = "cannot open shard: " + path;
        break;
      }
      bool shard_ok = true;
      bool stopped = false;
      for (;;) {
        uint32_t hdr[2];
        size_t got = std::fread(hdr, sizeof(uint32_t), 2, f);
        if (got == 0) break;  // clean EOF
        if (got == 2 && hdr[0] != kPageMagic) {
          // auto-detect the reference BinaryPage bit-format (io.h:225-300):
          // pages lead with the record count, not a magic, and the first
          // cumulative offset is always 0
          int rc = (hdr[1] == 0)
                       ? ReadRefPage(f, hdr[0], path, &seq, &err)
                       : 0;
          if (rc == 0) {
            if (err.empty())
              err = "corrupt page header in shard: " + path;
            shard_ok = false;
            break;
          }
          if (rc < 0) {
            stopped = true;
            break;
          }
          continue;
        }
        if (got != 2) {
          err = "corrupt page header in shard: " + path;
          shard_ok = false;
          break;
        }
        uint32_t nrec = hdr[1];
        if (nrec > kMaxRecordsPerPage) {
          err = "corrupt page (record count) in shard: " + path;
          shard_ok = false;
          break;
        }
        std::vector<uint32_t> lens(nrec);
        if (nrec && std::fread(lens.data(), sizeof(uint32_t), nrec, f) != nrec) {
          err = "truncated page in shard: " + path;
          shard_ok = false;
          break;
        }
        for (uint32_t i = 0; i < nrec && shard_ok; ++i) {
          if (lens[i] > kMaxRecordBytes) {
            err = "corrupt record length in shard: " + path;
            shard_ok = false;
            break;
          }
          Record r;
          r.seq = seq;
          r.blob.resize(lens[i]);
          if (std::fread(r.blob.data(), 1, lens[i], f) != lens[i]) {
            err = "truncated record in shard: " + path;
            shard_ok = false;
            break;
          }
          if (!PushRecord(std::move(r))) {
            stopped = true;
            break;
          }
          ++seq;
        }
        if (!shard_ok || stopped) break;
      }
      std::fclose(f);
      if (stopped) return;
      if (!shard_ok) break;
    }
    std::lock_guard<std::mutex> lk(mu_);
    if (!err.empty()) error_ = err;
    eof_seq_ = seq;
    reader_done_ = true;
    cv_in_.notify_all();
    cv_out_.notify_all();
  }

  void DecodeLoop() {
    for (;;) {
      Record r;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_in_.wait(lk, [this] { return stop_ || !in_.empty() || reader_done_; });
        if (stop_) return;
        if (in_.empty()) return;  // reader done and drained
        r = std::move(in_.front());
        in_.pop_front();
        cv_in_.notify_all();
      }
      if (!LooksLikeJpeg(r.blob) || !DecodeJpeg(r.blob, &r)) {
        r.kind = 0;  // pass through; Python decodes
      } else {
        r.blob.clear();
        r.blob.shrink_to_fit();
      }
      std::unique_lock<std::mutex> lk(mu_);
      cv_out_.wait(lk, [this, &r] {
        return stop_ || out_.size() < kOutWindowCap || r.seq == consume_seq_;
      });
      if (stop_) return;
      out_.emplace(r.seq, std::move(r));
      cv_out_.notify_all();
    }
  }

  std::vector<std::string> paths_;
  int ndecode_;
  std::thread reader_;
  std::vector<std::thread> workers_;
  std::mutex mu_;
  std::condition_variable cv_in_, cv_out_;
  std::deque<Record> in_;
  std::map<uint64_t, Record> out_;
  bool stop_ = true;
  bool reader_done_ = false;
  std::string error_;
  uint64_t eof_seq_ = UINT64_MAX;
  uint64_t consume_seq_ = 0;
};

struct Handle {
  Pipeline* pipe = nullptr;
  Record cur;
  std::string err_buf;
};

}  // namespace

extern "C" {

void* cxio_open(const char* paths_nl, int ndecode) {
  std::vector<std::string> paths;
  std::string s(paths_nl ? paths_nl : "");
  size_t pos = 0;
  while (pos < s.size()) {
    size_t nl = s.find('\n', pos);
    if (nl == std::string::npos) nl = s.size();
    if (nl > pos) paths.emplace_back(s.substr(pos, nl - pos));
    pos = nl + 1;
  }
  if (paths.empty()) return nullptr;
  auto* h = new Handle();
  h->pipe = new Pipeline(std::move(paths), ndecode);
  h->pipe->Start();
  return h;
}

void cxio_reset(void* hv) {
  auto* h = static_cast<Handle*>(hv);
  h->pipe->Start();
}

// Returns the persistent reader error ("" when healthy).  The returned
// buffer lives in the handle and is valid until the next call.
const char* cxio_error(void* hv) {
  auto* h = static_cast<Handle*>(hv);
  h->err_buf = h->pipe->Error();
  return h->err_buf.c_str();
}

int cxio_next(void* hv) {
  auto* h = static_cast<Handle*>(hv);
  return h->pipe->Next(&h->cur) ? 1 : 0;
}

int cxio_kind(void* hv) { return static_cast<Handle*>(hv)->cur.kind; }

void cxio_shape(void* hv, int* hh, int* ww, int* cc) {
  auto* h = static_cast<Handle*>(hv);
  *hh = h->cur.h;
  *ww = h->cur.w;
  *cc = h->cur.c;
}

long cxio_size(void* hv) {
  auto* h = static_cast<Handle*>(hv);
  return h->cur.kind ? static_cast<long>(h->cur.pixels.size())
                     : static_cast<long>(h->cur.blob.size());
}

long cxio_copy(void* hv, unsigned char* out, long cap) {
  auto* h = static_cast<Handle*>(hv);
  const auto& src = h->cur.kind ? h->cur.pixels : h->cur.blob;
  long n = static_cast<long>(src.size());
  if (n > cap) return -1;
  std::memcpy(out, src.data(), n);
  return n;
}

void cxio_close(void* hv) {
  auto* h = static_cast<Handle*>(hv);
  delete h->pipe;
  delete h;
}

}  // extern "C"
