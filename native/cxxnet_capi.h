/*!
 * C ABI for the cxxnet-tpu framework — the non-Python host surface.
 *
 * Parity: /root/reference/wrapper/cxxnet_wrapper.h:36-230 (CXNIO* data
 * iterator + CXNNet* trainer families).  Implemented by embedding
 * CPython (native/cxxnet_capi.cc): the library initializes an
 * interpreter on first use, imports cxxnet_tpu.capi_shim, and forwards
 * each call.  The compute still runs the framework's jitted XLA
 * programs — this is a host-language binding, not a second engine.
 *
 * Layout note: the reference is NCHW; this framework is NHWC
 * (TPU-native).  4-D shapes are (n, h, w, c); flat data is
 * (n, 1, 1, d).  All buffers are C-contiguous float32 and remain valid
 * until the next call on the same handle (reference temp-buffer rule).
 *
 * Errors: failed calls return NULL/-1 and set a message readable with
 * CXNGetLastError() (the reference aborted the process instead).
 */
#ifndef CXXNET_TPU_CAPI_H_
#define CXXNET_TPU_CAPI_H_

#ifdef __cplusplus
extern "C" {
#endif

typedef float cxx_real_t;
typedef unsigned cxx_uint;

/*! \brief message for the last failed call on this thread */
const char *CXNGetLastError(void);

/* ------------------------------------------------------ data iterator */
/*! \brief create an io iterator from a config string (iter = ... blocks) */
void *CXNIOCreateFromConfig(const char *cfg);
/*! \brief move to the next batch; returns 0 at end of epoch, -1 on error */
int CXNIONext(void *handle);
/*! \brief rewind the iterator */
void CXNIOBeforeFirst(void *handle);
/*! \brief current batch data; fills oshape[4] = (n, h, w, c) */
const cxx_real_t *CXNIOGetData(void *handle, cxx_uint oshape[4],
                               cxx_uint *ostride);
/*! \brief current batch labels; fills oshape[2] = (n, label_width) */
const cxx_real_t *CXNIOGetLabel(void *handle, cxx_uint oshape[2],
                                cxx_uint *ostride);
/*! \brief free the iterator handle */
void CXNIOFree(void *handle);

/* -------------------------------------------------------------- net */
/*! \brief create a net; device may be NULL (config decides) */
void *CXNNetCreate(const char *device, const char *cfg);
void CXNNetFree(void *handle);
int CXNNetSetParam(void *handle, const char *name, const char *val);
int CXNNetInitModel(void *handle);
int CXNNetSaveModel(void *handle, const char *fname);
int CXNNetLoadModel(void *handle, const char *fname);
int CXNNetStartRound(void *handle, int round);
/*! \brief one training step on a raw batch: data (n, h, w, c) or
 *  (n, 1, 1, d) flat, labels (n, label_width) */
int CXNNetUpdateBatch(void *handle, const cxx_real_t *p_data,
                      const cxx_uint dshape[4], const cxx_real_t *p_label,
                      const cxx_uint lshape[2]);
/*! \brief one training step consuming the iterator's current batch */
int CXNNetUpdateIter(void *handle, void *data_handle);
/*! \brief per-instance predictions (argmax / raw value), length *out_size */
const cxx_real_t *CXNNetPredictBatch(void *handle, const cxx_real_t *p_data,
                                     const cxx_uint dshape[4],
                                     cxx_uint *out_size);
const cxx_real_t *CXNNetPredictIter(void *handle, void *data_handle,
                                    cxx_uint *out_size);
/*! \brief extract a named node's activations, (n, feature) flattened */
const cxx_real_t *CXNNetExtractBatch(void *handle, const cxx_real_t *p_data,
                                     const cxx_uint dshape[4],
                                     const char *node_name,
                                     cxx_uint oshape[2]);
const cxx_real_t *CXNNetExtractIter(void *handle, void *data_handle,
                                    const char *node_name,
                                    cxx_uint oshape[2]);
/*! \brief run the metric set over an eval iterator; returns the
 *  "\tname-metric:value" line (reference format) */
const char *CXNNetEvaluate(void *handle, void *data_handle,
                           const char *data_name);
/*! \brief set a weight from a 2-D view (reference visitor layout) */
int CXNNetSetWeight(void *handle, const cxx_real_t *p_weight,
                    cxx_uint size_weight, const char *layer_name,
                    const char *wtag);
/*! \brief get a weight as a 2-D view; fills oshape[2] */
const cxx_real_t *CXNNetGetWeight(void *handle, const char *layer_name,
                                  const char *wtag, cxx_uint oshape[2]);

#ifdef __cplusplus
}
#endif
#endif  /* CXXNET_TPU_CAPI_H_ */
