"""Python-API walkthrough: train the MNIST-recipe MLP without a .conf
task driver — the analog of the reference's wrapper example
(``/root/reference/example/MNIST/mnist.py``), updated for this
framework's packaging and the zero-egress digits data
(``./run.sh digits.conf`` generates ``data/`` first).  For real 28x28
MNIST ubyte files set ``MNIST_DIM=784`` (the pixel count flows into
``input_shape``).
"""

import os

import numpy as np

from cxxnet_tpu import DataIter, train

DIM = int(os.environ.get("MNIST_DIM", "64"))  # 64 = 8x8 digits, 784 = MNIST

ITER_TMPL = """
iter = mnist
    path_img = "./data/{img}"
    path_label = "./data/{lab}"
    {extra}
iter = end
input_shape = 1,1,{dim}
batch_size = 50
"""

NET_CFG = """
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 100
  init_sigma = 0.01
layer[+1:sg1] = sigmoid:se1
layer[sg1->fc2] = fullc:fc2
  nhidden = 10
  init_sigma = 0.01
layer[+0] = softmax
netconfig=end

input_shape = 1,1,{dim}
batch_size = 50
eta = 0.1
momentum = 0.9
metric = error
dev = cpu
"""


def main() -> None:
    data = DataIter(ITER_TMPL.format(
        img="train-images-idx3-ubyte", lab="train-labels-idx1-ubyte",
        extra="shuffle = 1", dim=DIM,
    ))
    deval = DataIter(ITER_TMPL.format(
        img="t10k-images-idx3-ubyte", lab="t10k-labels-idx1-ubyte",
        extra="", dim=DIM,
    ))
    net = train(NET_CFG.format(dim=DIM), data, num_round=15, param={},
                eval_data=deval)

    # numpy-in / numpy-out prediction on the first eval batch
    deval.before_first()
    deval.next()
    batch = deval.value()
    pred = net.predict(np.asarray(batch.data))
    err = float((pred != batch.label[:, 0]).mean())
    print(f"first-batch error: {err:.3f}")

    # weight access through the 2-D visitor view
    w = net.get_weight("fc1", "wmat")
    print(f"fc1 wmat: {w.shape}")


if __name__ == "__main__":
    main()
