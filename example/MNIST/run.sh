#!/bin/bash
# Fetch-or-generate the digit data, then train from a conf.
#   ./run.sh MNIST.conf        # needs the MNIST ubyte files (downloads)
#   ./run.sh digits.conf       # zero-egress: real UCI digits, generated
set -eo pipefail
cd "$(dirname "$0")"

mkdir -p data models

if [ "$1" = "digits.conf" ]; then
    # real handwritten digits bundled with scikit-learn, idx-encoded
    python ../../tools/make_digits_idx.py data
else
    for f in train-images-idx3-ubyte train-labels-idx1-ubyte \
             t10k-images-idx3-ubyte t10k-labels-idx1-ubyte; do
        if [ ! -f "data/$f" ]; then
            # download to a temp name so an interrupted transfer never
            # leaves a truncated file the -f guard would then skip
            wget -O - "https://ossci-datasets.s3.amazonaws.com/mnist/$f.gz" \
                | gzip -d > "data/$f.tmp"
            mv "data/$f.tmp" "data/$f"
        fi
    done
fi

python -m cxxnet_tpu "${1:-MNIST.conf}" "${@:2}"
