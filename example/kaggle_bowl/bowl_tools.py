"""Kaggle NDSB (plankton) workflow helpers — one tool, four subcommands.

The full round-trip of the reference example
(``/root/reference/example/kaggle_bowl/README.md``): resize the class
folders, build shuffled .lst files, pack with ``tools/im2bin.py``, train
``bowl.conf``, predict with ``pred.conf`` (``task = pred_raw`` writes
softmax rows), then build the submission csv.  Replaces the reference's
four python-2 scripts (gen_train.py / gen_test.py / gen_img_list.py /
make_submission.py, rewritten — PIL instead of shelling out to
ImageMagick, csv module throughout) and gen_tr_va.sh.

    python bowl_tools.py resize  IN_DIR OUT_DIR [--size 48]
    python bowl_tools.py genlist train|test sampleSubmission.csv DIR OUT.lst
    python bowl_tools.py split   IN.lst TR.lst VA.lst [--n-train 20000]
    python bowl_tools.py submission sampleSubmission.csv test.lst \
        test.txt out.csv
"""

from __future__ import annotations

import argparse
import csv
import os
import random
import sys


def cmd_resize(args) -> None:
    """Resize every image under IN_DIR (flat, or one folder per class)
    to size x size (aspect ignored, reference parity) into OUT_DIR."""
    from PIL import Image

    todo = []
    for root, _dirs, files in os.walk(args.input):
        rel = os.path.relpath(root, args.input)
        for f in files:
            todo.append((os.path.join(root, f),
                         os.path.join(args.output, rel, f)))
    for src, dst in todo:
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        try:
            Image.open(src).convert("RGB").resize(
                (args.size, args.size)
            ).save(dst)
        except OSError as e:
            print(f"skip {src}: {e}", file=sys.stderr)
    print(f"resized {len(todo)} images to {args.size}x{args.size}")


def _class_order(sample_csv: str) -> list:
    with open(sample_csv, newline="") as f:
        head = next(csv.reader(f))
    return head[1:]  # first column is 'image'


def cmd_genlist(args) -> None:
    """Shuffled tab-separated ``index\\tlabel\\tpath`` list.

    train: one folder per class under DIR, labels ordered by the
    sampleSubmission header (the class-column order the submission
    needs).  test: flat folder, label 0.
    """
    rng = random.Random(888)
    rows = []
    if args.task == "train":
        for label, cls in enumerate(_class_order(args.sample)):
            cdir = os.path.join(args.folder, cls)
            for img in sorted(os.listdir(cdir)):
                rows.append((label, os.path.join(cdir, img)))
    else:
        for img in sorted(os.listdir(args.folder)):
            rows.append((0, os.path.join(args.folder, img)))
    rng.shuffle(rows)
    with open(args.out, "w", newline="") as f:
        w = csv.writer(f, delimiter="\t", lineterminator="\n")
        for i, (label, path) in enumerate(rows):
            w.writerow((i, label, path))
    print(f"wrote {len(rows)} entries to {args.out}")


def cmd_split(args) -> None:
    """Head/tail split of a .lst into train/validation (gen_tr_va.sh)."""
    with open(args.input) as f:
        lines = f.readlines()
    with open(args.train, "w") as f:
        f.writelines(lines[: args.n_train])
    with open(args.val, "w") as f:
        f.writelines(lines[args.n_train :])
    print(
        f"split {len(lines)} -> {min(args.n_train, len(lines))} train, "
        f"{max(0, len(lines) - args.n_train)} val"
    )


def cmd_submission(args) -> None:
    """Join test.lst image names with pred_raw softmax rows into the
    submission csv (header + image,prob...,prob per row)."""
    with open(args.sample, newline="") as f:
        head = next(csv.reader(f))
    names = []
    with open(args.lst, newline="") as f:
        for row in csv.reader(f, delimiter="\t"):
            if row:
                names.append(os.path.basename(row[-1]))
    n = 0
    with open(args.probs, newline="") as fi, open(
        args.out, "w", newline=""
    ) as fo:
        w = csv.writer(fo, lineterminator="\n")
        w.writerow(head)
        for line in fi:
            vals = line.split()
            if not vals:
                continue
            if len(vals) != len(head) - 1:
                raise ValueError(
                    f"row {n}: {len(vals)} probabilities for "
                    f"{len(head) - 1} classes"
                )
            if n >= len(names):
                raise ValueError(
                    f"{len(names)} test images but more prediction rows"
                )
            w.writerow([names[n]] + vals)
            n += 1
    if n != len(names):
        raise ValueError(f"{len(names)} test images but {n} prediction rows")
    print(f"wrote {n} rows to {args.out}")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)

    r = sub.add_parser("resize")
    r.add_argument("input")
    r.add_argument("output")
    r.add_argument("--size", type=int, default=48)
    r.set_defaults(fn=cmd_resize)

    g = sub.add_parser("genlist")
    g.add_argument("task", choices=("train", "test"))
    g.add_argument("sample")
    g.add_argument("folder")
    g.add_argument("out")
    g.set_defaults(fn=cmd_genlist)

    s = sub.add_parser("split")
    s.add_argument("input")
    s.add_argument("train")
    s.add_argument("val")
    s.add_argument("--n-train", type=int, default=20000)
    s.set_defaults(fn=cmd_split)

    m = sub.add_parser("submission")
    m.add_argument("sample")
    m.add_argument("lst")
    m.add_argument("probs")
    m.add_argument("out")
    m.set_defaults(fn=cmd_submission)

    args = p.parse_args(argv)
    args.fn(args)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
