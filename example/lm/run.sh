#!/bin/sh
# end-to-end demo on a public-domain-style synthetic corpus
set -e
python - << 'PY'
text = ("the quick brown fox jumps over the lazy dog. " * 300).encode()
open("corpus.txt", "wb").write(text)
from cxxnet_tpu.models import transformer_lm_conf
open("lm.conf", "w").write(transformer_lm_conf(
    seq_len=32, dim=64, nhead=2, nlayer=2,
    text_file="corpus.txt", batch_size=16, num_round=12))
PY
python -m cxxnet_tpu lm.conf task=train
python -m cxxnet_tpu lm.conf task=generate model_in=./models/0012.model \
    gen_prompt="the quick " gen_len=90
