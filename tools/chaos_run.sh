#!/usr/bin/env bash
# Chaos suite: run the fault matrix — every registered injection site ×
# every fault kind it supports — as SEPARATE pytest lanes (one process
# per lane, so a hang/crash in one lane cannot mask or poison another),
# then the chaos-marked scenario tests (real on-disk corruption, drain
# under load, the end-to-end corrupt-data training run).
#
# The acceptance contract (ISSUE 3): a triggered fault must resolve per
# policy — skip / retry / drain / degrade — never a hang, a silent
# drop, or an unhandled crash.
#
# The serve.replica lanes spawn supervised replica subprocesses (the
# fleet supervisor must restart a crashed replica and eject a wedged
# one within the probe deadline while requests keep succeeding via
# router failover); the heavyweight real-checkpoint variant is the
# FLEET=1 lane (tools/fleet_smoke.py).
#
# Usage: tools/chaos_run.sh            # full matrix + chaos-marked tests
# Wired into tier-1 as an opt-in stage: CHAOS=1 tools/run_tier1.sh
set -o pipefail
cd "$(dirname "$0")/.."

PYTEST_FLAGS="-q -p no:cacheprovider -p no:xdist -p no:randomly"
LANE_TIMEOUT=240
fail=0

lanes=$(env JAX_PLATFORMS=cpu python -c '
from cxxnet_tpu.utils.faults import SITES
for site, kinds in SITES.items():
    for kind in kinds:
        print(f"{site}-{kind}")
') || { echo "chaos: cannot enumerate the fault-site registry"; exit 1; }

for lane in $lanes; do
  echo "=== chaos lane: $lane ==="
  if ! timeout -k 10 "$LANE_TIMEOUT" env JAX_PLATFORMS=cpu \
      python -m pytest "tests/test_faults.py::test_fault_matrix[$lane]" \
      $PYTEST_FLAGS; then
    echo "!!! chaos lane FAILED: $lane"
    fail=1
  fi
done

echo "=== chaos lane: marked scenarios (-m chaos) ==="
if ! timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -m chaos $PYTEST_FLAGS; then
  echo "!!! chaos scenario lane FAILED"
  fail=1
fi

if [ "$fail" -ne 0 ]; then
  echo "CHAOS: FAILED (see lanes above)"
else
  echo "CHAOS: all lanes passed"
fi
exit $fail
