"""ELASTIC=1 lane: kill-one-process elastic recovery with bitwise parity.

The elastic-pod acceptance (ROADMAP item 3, doc/parallel.md "Elastic
pod"), proven end to end through the real CLI on a 4-process CPU mesh:

* **Run A (churn)** — 4 ``jax.distributed`` processes (1 CPU device
  each) train the MNIST-format MLP conf with ``elastic = 1``.  One
  NON-ZERO rank is SIGKILLed mid-round; the survivors must detect the
  loss, tear down, re-init as a 3-process mesh inside the same CLI
  invocation, reload the consensus round, and keep training.  A fifth
  process launched with ``elastic_join = 1`` waits out the churn and is
  admitted at a pinned later boundary, growing the mesh back to 4.
* **Run B (planned)** — the SAME shrink-at-k / grow-at-j schedule
  executed deliberately (``elastic_drop_at`` = run A's observed resume
  round; ``elastic_join_at`` unchanged), with no kill anywhere.
* **Parity** — every checkpoint manifest CRC32 the two runs write must
  be IDENTICAL.  ``det_reduce = 1`` pins the gradient-reduction order
  via the shard_map re-expression, ``dist_shard = block`` +
  ``RecordRNG`` pin the input stream, and ``save_ustate = 1`` carries
  the updater state across every rebuild — so a run that lost a replica
  is bit-equal to one that resized on purpose.
* The verdict JSON (rebuild wall time, recovered samples/sec, CRC
  equality) appends to a ``perf_guard`` history (``--bench elastic``)
  so recovery cost is regression-tracked.

Usage::

    python tools/elastic_kill.py --out /tmp/_elastic       # the CI lane
    python tools/perf_guard.py --bench elastic \\
        --input /tmp/_elastic/elastic.json --history bench_history.jsonl

Exit code: 0 when the schedule replayed and every CRC matches; 1
otherwise (hard gate, not weather).
"""

from __future__ import annotations

import argparse
import json
import os
import re
import signal
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

NUM_ROUND = 8
GLOBAL_BATCH = 12          # divides 4-way AND 3-way data meshes
N_IMAGES = 960             # 80 global batches/round; blocks tile 4 and 3
N_HIDDEN = 256             # enough per-round work to kill mid-round
KILL_AFTER_CKPT = 3        # SIGKILL once 0003.model is durable
JOIN_AT = 7                # grow boundary (start_counter units)
KILL_RANK = 3              # never rank 0 (it hosts both coordinators)
# --kill-checkpoint mode: shrink 3 -> 2 at this boundary, then SIGKILL
# rank 0 INSIDE the first post-rebuild consensus checkpoint write
CKPT_DROP_AT = 4
KILL_CKPT_ROUND = 5


def _free_port() -> int:
    from cxxnet_tpu.parallel.elastic import free_port

    return free_port()


def make_data(out_dir: str) -> None:
    import numpy as np

    from cxxnet_tpu.io.mnist import write_idx_images, write_idx_labels

    rng = np.random.RandomState(0)
    imgs = rng.randint(0, 256, (N_IMAGES, 4, 4)).astype(np.uint8)
    labels = (imgs.reshape(N_IMAGES, -1).mean(1) > 127).astype(np.uint8)
    write_idx_images(os.path.join(out_dir, "img.idx"), imgs)
    write_idx_labels(os.path.join(out_dir, "lab.idx"), labels)


def make_conf(out_dir: str) -> str:
    """One conf for every process of both runs; per-run/per-rank keys
    ride as CLI overrides.  ``model_dir`` is overridden to a SHARED
    absolute path per run (the consensus reload and the joiner both
    read rank 0's checkpoints)."""
    conf = os.path.join(out_dir, "elastic.conf")
    with open(conf, "w", encoding="utf-8") as f:
        f.write(f"""
data = train
iter = mnist
  path_img = "{out_dir}/img.idx"
  path_label = "{out_dir}/lab.idx"
  shuffle = 1
  dist_shard = block
iter = end
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = {N_HIDDEN}
  init_sigma = 0.1
layer[fc1->out] = fullc:fc2
  nhidden = 2
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,16
batch_size = {GLOBAL_BATCH}
dev = cpu
num_round = {NUM_ROUND}
eval_train = 0
eta = 0.1
momentum = 0.9
seed = 7
save_ustate = 1
det_reduce = 1
metric = error
silent = 1
telemetry = 1
elastic = 1
elastic_min_replicas = 2
elastic_heartbeat_s = 0.25
elastic_timeout_s = 3
collective_timeout_s = 30
""")
    return conf


def launch_rank(conf: str, workdir: str, model_dir: str, rank: int,
                nproc: int, jax_port: int, elastic_port: int,
                extra=(), extra_env=None):
    d = os.path.join(workdir, f"p{rank}")
    os.makedirs(d, exist_ok=True)
    env = {
        **os.environ,
        "PYTHONPATH": REPO,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
    }
    if extra_env:
        env.update(extra_env)
    over = [f"model_dir={model_dir}",
            f"elastic_coordinator=localhost:{elastic_port}"]
    if rank >= 0:
        over += [f"dist_coordinator=localhost:{jax_port}",
                 f"dist_num_proc={nproc}", f"dist_proc_id={rank}"]
    over += list(extra)
    log = open(os.path.join(d, "out.log"), "wb")
    p = subprocess.Popen(
        [sys.executable, "-u", "-m", "cxxnet_tpu", conf] + over,
        env=env, cwd=d, stdout=log, stderr=subprocess.STDOUT,
    )
    p._log_file = log  # type: ignore[attr-defined]
    p._workdir = workdir  # type: ignore[attr-defined]
    p._rank = rank     # type: ignore[attr-defined]
    return p


def rank_log(workdir: str, rank: int) -> str:
    try:
        with open(os.path.join(workdir, f"p{rank}", "out.log"), "r",
                  encoding="utf-8", errors="replace") as f:
            return f.read()
    except OSError:
        return ""


def wait_for_checkpoint(model_dir: str, round_: int, procs,
                        timeout: float) -> bool:
    """Block until ``<round>.model``'s manifest is durable (or every
    process exited / the budget ran out)."""
    from cxxnet_tpu.utils import checkpoint as ckpt

    want = ckpt.manifest_path(
        os.path.join(model_dir, f"{round_:04d}.model"))
    t0 = time.time()
    while time.time() - t0 < timeout:
        if os.path.exists(want):
            return True
        if all(p.poll() is not None for p in procs):
            return False
        time.sleep(0.05)
    return False


def drain(procs, timeout: float, problems, tag: str,
          expect_fail_ranks=()):
    deadline = time.time() + timeout
    for p in procs:
        left = max(1.0, deadline - time.time())
        try:
            p.wait(timeout=left)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()
            problems.append(f"{tag}: rank {p._rank} process timed out")
        finally:
            p._log_file.close()
    for p in procs:
        if p._rank in expect_fail_ranks:
            continue
        if p.returncode != 0:
            problems.append(
                f"{tag}: rank {p._rank} exited rc={p.returncode}; "
                "tail:\n" + rank_log(p._workdir, p._rank)[-2500:])


def read_crcs(model_dir: str) -> dict:
    from cxxnet_tpu.utils import checkpoint as ckpt

    out = {}
    for round_, path in ckpt.list_checkpoints(model_dir):
        man = ckpt.read_manifest(path)
        if man is not None:
            out[round_] = man["crc32"]
    return out


def read_telemetry(workdir: str, rank: int = 0) -> list:
    path = os.path.join(workdir, f"p{rank}", "telemetry.jsonl")
    recs = []
    try:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line:
                    recs.append(json.loads(line))
    except (OSError, ValueError):
        pass
    return recs


def run_churn(conf: str, workdir: str, model_dir: str,
              timeout: float, problems) -> dict:
    """Run A: 4 ranks + 1 waiting joiner; SIGKILL one rank mid-round."""
    os.makedirs(model_dir, exist_ok=True)
    jax_port, elastic_port = _free_port(), _free_port()
    procs = [launch_rank(conf, workdir, model_dir, r, 4, jax_port,
                         elastic_port) for r in range(4)]
    joiner = launch_rank(
        conf, workdir, model_dir, -1, 0, jax_port, elastic_port,
        extra=["elastic_join=1", f"elastic_join_at={JOIN_AT}",
               "elastic_rejoin_s=240", "dist_shard=block"])
    killed_at = None
    if wait_for_checkpoint(model_dir, KILL_AFTER_CKPT, procs,
                           timeout=timeout / 2):
        time.sleep(0.2)  # let the next round get airborne
        procs[KILL_RANK].send_signal(signal.SIGKILL)
        killed_at = time.time()
        print(f"churn: SIGKILLed rank {KILL_RANK} after checkpoint "
              f"{KILL_AFTER_CKPT:04d}.model", flush=True)
    else:
        problems.append(
            f"churn: checkpoint {KILL_AFTER_CKPT:04d}.model never "
            "appeared; cannot stage the kill")
    drain(procs + [joiner], timeout, problems, "churn",
          expect_fail_ranks={KILL_RANK})
    if procs[KILL_RANK].returncode == 0:
        problems.append("churn: the killed rank exited 0 — the kill "
                        "landed after training finished (too late)")
    log0 = rank_log(workdir, 0)
    resume = [int(m) for m in re.findall(
        r"replica_lost -> rebuilding.*?\n.*?resuming at round (\d+)",
        log0, re.S)]
    grows = [int(m) for m in re.findall(
        r"grow -> rebuilding.*?\n.*?resuming at round (\d+)", log0, re.S)]
    if not resume:
        problems.append("churn: rank 0 never rebuilt after the kill; "
                        "log tail:\n" + log0[-2500:])
    if not grows:
        problems.append("churn: the mesh never grew back (joiner log "
                        "tail:\n" + rank_log(workdir, -1)[-1500:] + ")")
    tele = read_telemetry(workdir)
    rebuild_s = max((r.get("elastic", {}).get("last_rebuild_s", 0.0)
                     for r in tele), default=0.0)
    post = [r for r in tele if r.get("elastic", {}).get("rebuilds", 0)]
    rate = (post[-1].get("step", {}).get("samples_per_sec", 0.0)
            if post else 0.0)
    return {
        "resume_round": resume[0] if resume else None,
        "grow_round": grows[0] if grows else None,
        "rebuild_wall_s": rebuild_s,
        "recovered_samples_per_sec": rate,
        "kill_staged": killed_at is not None,
    }


def run_planned(conf: str, workdir: str, model_dir: str, drop_at: int,
                join_at: int, timeout: float, problems) -> dict:
    """Run B: the identical schedule, resized on purpose (no kill)."""
    os.makedirs(model_dir, exist_ok=True)
    jax_port, elastic_port = _free_port(), _free_port()
    procs = [launch_rank(conf, workdir, model_dir, r, 4, jax_port,
                         elastic_port, extra=[f"elastic_drop_at={drop_at}"])
             for r in range(4)]
    joiner = launch_rank(
        conf, workdir, model_dir, -1, 0, jax_port, elastic_port,
        extra=["elastic_join=1", f"elastic_join_at={join_at}",
               "elastic_rejoin_s=240", "dist_shard=block"])
    drain(procs + [joiner], timeout, problems, "planned")
    log3 = rank_log(workdir, 3)
    if "left the mesh" not in log3:
        problems.append("planned: rank 3 never executed the planned "
                        "departure; log tail:\n" + log3[-2000:])
    tele = read_telemetry(workdir)
    rebuild_s = max((r.get("elastic", {}).get("last_rebuild_s", 0.0)
                     for r in tele), default=0.0)
    return {"rebuild_wall_s": rebuild_s}


def run_kill_checkpoint(conf: str, workdir: str, model_dir: str,
                        timeout: float, problems) -> dict:
    """--kill-checkpoint: kill -9 INSIDE the consensus checkpoint write.

    A 3-rank pod shrinks to 2 at ``CKPT_DROP_AT`` (planned departure);
    rank 0 carries ``CXXNET_DISKIO_KILL_AT=<round>.model:2``, so the
    SIGKILL lands deterministically between the checkpoint temp file's
    fsync and its ``os.replace`` — the torn temp is on disk, the
    published name is not.  The survivors are then killed too (a
    whole-pod power loss).  A fresh 2-rank pod restarts with
    ``continue=1`` and must resume from the prior consensus round with
    every surviving manifest CRC-valid — the crash-audit atomic-publish
    invariant proven through the real CLI."""
    from cxxnet_tpu.utils import checkpoint as ckpt
    from cxxnet_tpu.utils import diskio

    os.makedirs(model_dir, exist_ok=True)
    jax_port, elastic_port = _free_port(), _free_port()
    kill_env = {diskio.KILL_ENV: f"{KILL_CKPT_ROUND:04d}.model:2"}
    procs = [launch_rank(conf, workdir, model_dir, r, 3, jax_port,
                         elastic_port,
                         extra=[f"elastic_drop_at={CKPT_DROP_AT}"],
                         extra_env=kill_env if r == 0 else None)
             for r in range(3)]
    t0 = time.time()
    while procs[0].poll() is None and time.time() - t0 < timeout:
        time.sleep(0.1)
    if procs[0].poll() is None:
        problems.append("kill-checkpoint: rank 0 never hit the staged "
                        "kill inside the round-"
                        f"{KILL_CKPT_ROUND} checkpoint write")
    for p in procs[1:]:
        p.send_signal(signal.SIGKILL)
    drain(procs, 60, problems, "kill-checkpoint",
          expect_fail_ranks={0, 1, 2})
    if procs[0].returncode != -signal.SIGKILL:
        problems.append("kill-checkpoint: rank 0 exited "
                        f"rc={procs[0].returncode}, expected SIGKILL; "
                        "tail:\n" + rank_log(workdir, 0)[-2000:])

    # crash window: torn temp on disk, published name absent, every
    # surviving checkpoint CRC-valid, resume target = the prior round
    target = os.path.join(model_dir, f"{KILL_CKPT_ROUND:04d}.model")
    tmp_orphan = any(f".{KILL_CKPT_ROUND:04d}.model.tmp." in n
                     for n in os.listdir(model_dir))
    if os.path.exists(target):
        problems.append(f"kill-checkpoint: {os.path.basename(target)} "
                        "was published despite the mid-write kill")
    if not tmp_orphan:
        problems.append("kill-checkpoint: no torn temp file — the kill "
                        "did not land inside the write")
    for round_, path in ckpt.list_checkpoints(model_dir):
        reason = ckpt.validate_checkpoint(path)
        if reason is not None:
            problems.append(f"kill-checkpoint: surviving round {round_} "
                            f"invalid after crash: {reason}")
    latest = ckpt.find_latest_valid(model_dir, silent=True)
    if latest is None or latest[0] != KILL_CKPT_ROUND - 1:
        problems.append("kill-checkpoint: resume target is "
                        f"{latest and latest[0]}, expected consensus "
                        f"round {KILL_CKPT_ROUND - 1}")

    # restart: a fresh 2-rank pod continues from the consensus round
    t1 = time.time()
    restart_dir = os.path.join(workdir, "restart")
    jax_port, elastic_port = _free_port(), _free_port()
    rprocs = [launch_rank(conf, restart_dir, model_dir, r, 2, jax_port,
                          elastic_port, extra=["continue=1"])
              for r in range(2)]
    drain(rprocs, timeout, problems, "kill-checkpoint-restart")
    restart_s = time.time() - t1
    log0 = rank_log(restart_dir, 0)
    resumed = f"Continue training from round {KILL_CKPT_ROUND}" in log0
    if not resumed:
        problems.append("kill-checkpoint: restart did not resume from "
                        f"round {KILL_CKPT_ROUND - 1} (expected 'Continue "
                        f"training from round {KILL_CKPT_ROUND}'); "
                        "tail:\n" + log0[-2000:])
    crcs = read_crcs(model_dir)
    if len(crcs) != NUM_ROUND + 1:
        problems.append("kill-checkpoint: restart finished with rounds "
                        f"{sorted(crcs)}, expected {NUM_ROUND + 1} "
                        "checkpoints")
    for round_, path in ckpt.list_checkpoints(model_dir):
        reason = ckpt.validate_checkpoint(path)
        if reason is not None:
            problems.append(f"kill-checkpoint: round {round_} invalid "
                            f"after restart: {reason}")
    return {
        "tmp_orphan": tmp_orphan,
        "resumed_from": (latest[0] if latest else None),
        "restart_wall_s": round(restart_s, 3),
        "rounds_final": len(crcs),
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="/tmp/_elastic",
                    help="scratch + verdict directory")
    ap.add_argument("--timeout", type=float, default=420.0,
                    help="per-run wall-clock budget (seconds)")
    ap.add_argument("--json", dest="json_path", default="",
                    help="verdict path (default <out>/elastic.json)")
    ap.add_argument("--kill-checkpoint", action="store_true",
                    help="run ONLY the kill-9-inside-the-consensus-"
                    "checkpoint-write crash window (verdict "
                    "<out>/elastic_crash.json)")
    args = ap.parse_args()

    if args.kill_checkpoint:
        os.makedirs(args.out, exist_ok=True)
        make_data(args.out)
        conf = make_conf(args.out)
        problems: list = []
        t0 = time.time()
        crash_dir = os.path.join(args.out, "killckpt")
        res = run_kill_checkpoint(
            conf, crash_dir, os.path.join(crash_dir, "models"),
            args.timeout, problems)
        doc = {
            "bench": "elastic_crash",
            "ts": time.time(),
            "wall_sec": round(time.time() - t0, 3),
            **res,
            "problems": problems,
            "verdict": "ok" if not problems else "fail",
        }
        json_path = args.json_path or os.path.join(args.out,
                                                   "elastic_crash.json")
        with open(json_path, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1)
        print(json.dumps(doc, indent=1))
        for p in problems:
            print(f"FAIL {p}", file=sys.stderr)
        return 1 if problems else 0

    os.makedirs(args.out, exist_ok=True)
    make_data(args.out)
    conf = make_conf(args.out)
    problems: list = []

    t0 = time.time()
    churn_dir = os.path.join(args.out, "churn")
    churn = run_churn(conf, churn_dir,
                      os.path.join(churn_dir, "models"),
                      args.timeout, problems)
    churn_s = time.time() - t0

    planned = {"rebuild_wall_s": 0.0}
    planned_s = 0.0
    crc_equal = False
    churn_crcs: dict = {}
    planned_crcs: dict = {}
    if churn["resume_round"] is not None and not problems:
        t1 = time.time()
        planned_dir = os.path.join(args.out, "planned")
        planned = run_planned(
            conf, planned_dir, os.path.join(planned_dir, "models"),
            drop_at=churn["resume_round"],
            join_at=churn["grow_round"] or JOIN_AT,
            timeout=args.timeout, problems=problems)
        planned_s = time.time() - t1
        churn_crcs = read_crcs(os.path.join(churn_dir, "models"))
        planned_crcs = read_crcs(os.path.join(planned_dir, "models"))
        if len(churn_crcs) != NUM_ROUND + 1:
            problems.append(
                f"churn run wrote rounds {sorted(churn_crcs)}, expected "
                f"{NUM_ROUND + 1} checkpoints")
        crc_equal = bool(churn_crcs) and churn_crcs == planned_crcs
        if not crc_equal:
            problems.append(
                "BITWISE PARITY FAILED: killed-and-recovered CRCs "
                f"{ {k: hex(v) for k, v in sorted(churn_crcs.items())} } "
                "!= planned-resize CRCs "
                f"{ {k: hex(v) for k, v in sorted(planned_crcs.items())} }")

    doc = {
        "bench": "elastic",
        "ts": time.time(),
        "rounds": NUM_ROUND,
        "global_batch": GLOBAL_BATCH,
        "resume_round": churn["resume_round"],
        "grow_round": churn["grow_round"],
        "crc_equal": crc_equal,
        "crcs": {str(k): f"{v:#010x}"
                 for k, v in sorted(churn_crcs.items())},
        "churn": {"wall_sec": round(churn_s, 3),
                  "rebuild_wall_s": churn["rebuild_wall_s"],
                  "recovered_samples_per_sec":
                      round(churn["recovered_samples_per_sec"], 2)},
        "planned": {"wall_sec": round(planned_s, 3),
                    "rebuild_wall_s": planned["rebuild_wall_s"]},
        "problems": problems,
        "verdict": "ok" if not problems else "fail",
    }
    json_path = args.json_path or os.path.join(args.out, "elastic.json")
    with open(json_path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1)
    print(json.dumps(doc, indent=1))
    for p in problems:
        print(f"FAIL {p}", file=sys.stderr)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
