"""GoogLeNet convergence proxy on synthetic-but-learnable imgbin data
(VERDICT r4 "What's missing" #4 / "Next round" #6).

Real ImageNet is unreachable from the sandbox (zero egress), so
"top-1 parity" (BASELINE.json) cannot be demonstrated directly.  This
is the strongest available stand-in beyond the one-batch overfit
smoke: a full multi-round training run of the real GoogLeNet conf
through the REAL input path (imgbin shard -> JPEG decode -> rand-crop/
mirror augment -> batch -> train), on a 10-class dataset whose signal
is genuinely visual — each class is a sinusoidal grating at a
class-specific spatial frequency, with random orientation, phase,
offset and pixel noise per image, so the net must learn a
texture-frequency discriminator rather than memorize pixels.  The
signal is crop- and mirror-invariant by construction, so augmentation
is exercised honestly.

What the committed trajectory proves: the full stack (pipeline,
augmentation, BN batch stats, inception topology, schedules) *learns*
— train/eval error fall from 90% (chance) toward ~0 over rounds, with
a held-out eval split.  What it does NOT prove: ImageNet-scale top-1;
that stays flagged until real data exists in the sandbox.

    python tools/convergence_proxy.py [n_train] [n_eval] [rounds] [batch]

Writes example/ImageNet/convergence_proxy.log (the committed artifact).
"""

import io
import os
import re
import subprocess
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

LOG_PATH = os.path.join(REPO, "example", "ImageNet", "convergence_proxy.log")

# class k -> grating wavelength in pixels (distinct, ratio ~1.23 apart
# so JPEG + bilinear survive the spacing)
WAVELENGTHS = [3.0, 3.7, 4.6, 5.7, 7.0, 8.7, 10.7, 13.2, 16.3, 20.2]


def generate_class_imgbin(workdir: str, prefix: str, n: int, size: int,
                          seed: int) -> None:
    """n JPEGs whose label is decodable only from texture frequency."""
    from PIL import Image

    from cxxnet_tpu.io.imgbin import BinPageWriter

    rng = np.random.RandomState(seed)
    writer = BinPageWriter(os.path.join(workdir, f"{prefix}.bin"))
    yy, xx = np.mgrid[0:size, 0:size].astype(np.float32)
    with open(os.path.join(workdir, f"{prefix}.lst"), "w") as lst:
        for i in range(n):
            k = int(rng.randint(10))
            wl = WAVELENGTHS[k]
            theta = rng.uniform(0, np.pi)          # orientation: nuisance
            phase = rng.uniform(0, 2 * np.pi)      # phase: nuisance
            u = xx * np.cos(theta) + yy * np.sin(theta)
            img = 128 + rng.uniform(50, 90) * np.sin(2 * np.pi * u / wl
                                                     + phase)
            img = img[..., None] + rng.uniform(-30, 30, (1, 1, 3))
            img += rng.randn(size, size, 3) * 10
            pil = Image.fromarray(
                np.clip(img, 0, 255).astype(np.uint8), "RGB")
            buf = io.BytesIO()
            pil.save(buf, "JPEG", quality=90)
            writer.push(buf.getvalue())
            lst.write(f"{i}\t{k}\tgrating_{i}.jpg\n")
    writer.close()


def main() -> None:
    n_train = int(sys.argv[1]) if len(sys.argv) > 1 else 1024
    n_eval = int(sys.argv[2]) if len(sys.argv) > 2 else 512
    rounds = int(sys.argv[3]) if len(sys.argv) > 3 else 10
    batch = int(sys.argv[4]) if len(sys.argv) > 4 else 16

    from cxxnet_tpu.models import googlenet_conf

    t0 = time.time()
    with tempfile.TemporaryDirectory() as workdir:
        generate_class_imgbin(workdir, "train", n_train, 80, seed=1)
        generate_class_imgbin(workdir, "eval", n_eval, 80, seed=2)
        conf = f"""
data = train
iter = imgbin
  image_bin = {workdir}/train.bin
  image_list = {workdir}/train.lst
  rand_crop = 1
  rand_mirror = 1
  shuffle = 1
  mean_value = 128,128,128
  divideby = 64
  input_shape = 3,64,64
  batch_size = {batch}
  round_batch = 1
  label_width = 1
iter = threadbuffer
iter = end
eval = test
iter = imgbin
  image_bin = {workdir}/eval.bin
  image_list = {workdir}/eval.lst
  mean_value = 128,128,128
  divideby = 64
  input_shape = 3,64,64
  batch_size = {batch}
  round_batch = 1
  label_width = 1
iter = end
""" + googlenet_conf(batch_size=batch, num_class=10, input_size=64,
                     synthetic=False, dev="cpu") + f"""
num_round = {rounds}
max_round = {rounds}
save_model = 0
eval_train = 1
metric = logloss
# the builder's sgd schedule is tuned for b128 ImageNet and diverges
# (NaN logits) at b{batch} on this 10-class set — the adam recipe the
# membuffer-overfit tests use on this exact model is the stable choice
updater = adam
eta = 0.001
wmat:lr = 0.001
bias:lr = 0.001
wd = 0.0001
"""
        conf_path = os.path.join(workdir, "proxy.conf")
        with open(conf_path, "w") as f:
            f.write(conf)
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO  # pure-CPU jax: never dials the relay
        env["JAX_PLATFORMS"] = "cpu"
        r = subprocess.run(
            [sys.executable, "-m", "cxxnet_tpu", conf_path, "task=train"],
            env=env, capture_output=True, text=True, cwd=workdir,
        )
        if r.returncode != 0:
            sys.stderr.write(r.stderr[-4000:])
            raise SystemExit(f"training failed rc={r.returncode}")
    rows = [ln for ln in r.stderr.splitlines()
            if re.match(r"\[\d+\]\t", ln)]
    lines = [
        f"# convergence_proxy @ "
        f"{time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime())}",
        f"# GoogLeNet (builders.googlenet_conf, 64px, b{batch}) on "
        f"{n_train}-image / 10-class frequency-grating imgbin, "
        f"held-out eval {n_eval}; full pipeline in-path "
        f"(decode -> rand-crop/mirror -> threadbuffer); "
        f"{rounds} rounds, CPU, {time.time() - t0:.0f}s total",
        "# chance level: error 0.900",
    ] + rows
    with open(LOG_PATH, "w") as f:
        f.write("\n".join(lines) + "\n")
    print("\n".join(lines))
    print(f"# wrote {LOG_PATH}")


if __name__ == "__main__":
    main()
