"""Observability smoke: short telemetry train + serve scrape, end to end.

The driver behind the ``OBS=1`` lane of ``tools/run_tier1.sh``
(doc/observability.md).  One process:

1. generates a tiny synthetic MNIST-style dataset and trains it for a
   couple of rounds with ``telemetry=1``, ``event_log``, ``trace_dir``,
   ``device_sample_every`` and a deliberately-tripped ``alert=`` rule
   armed — producing ``telemetry.jsonl`` (with per-round ``device``
   totals), ``events.jsonl`` and a Chrome host trace;
2. serves the checkpoint it just wrote (``serve/`` engine + HTTP
   front-end), drives a few ``/predict`` requests through the
   micro-batcher, walks the latency alert through fire (degraded
   ``/healthz``) and clear, and scrapes ``GET /metricsz`` /
   ``GET /alertz`` to ``<out>/metricsz.txt`` / ``<out>/alertz.json``;
3. prints the artifact paths — the lane then schema-validates them via
   ``tools/obs_dump.py --check`` (including the device-plane metric
   families pinned with ``--require``).

Usage:  python tools/obs_smoke.py --out /tmp/obs_smoke
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import urllib.request

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CONF_TEMPLATE = """
data = train
iter = mnist
  path_img = "{out}/data/tr-img.idx"
  path_label = "{out}/data/tr-lab.idx"
  shuffle = 1
iter = end
eval = test
iter = mnist
  path_img = "{out}/data/te-img.idx"
  path_label = "{out}/data/te-lab.idx"
iter = end

netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 32
  init_sigma = 0.1
layer[+1:sg1] = relu
layer[sg1->fc2] = fullc:fc2
  nhidden = 4
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end

input_shape = 1,1,64
batch_size = 64
dev = cpu
save_model = 1
num_round = 2
eval_train = 1
eta = 0.3
metric = error
model_dir = {out}/models
telemetry = 1
telemetry_path = {out}/telemetry.jsonl
event_log = {out}/events.jsonl
trace_dir = {out}/traces
trace_steps = 3
device_sample_every = 2
alert = smoke_latency:serve_request_latency_seconds_mean:>:0:0
silent = 1
"""


def make_data(out: str) -> None:
    from cxxnet_tpu.io.mnist import write_idx_images, write_idx_labels

    rng = np.random.RandomState(0)
    n, hw = 256, 8
    imgs = rng.randint(0, 256, (n, hw, hw)).astype(np.uint8)
    flat = imgs.reshape(n, -1).astype(np.float32)
    labels = (np.argsort(np.argsort(flat.mean(1))) * 4 // n).astype(np.uint8)
    os.makedirs(os.path.join(out, "data"), exist_ok=True)
    write_idx_images(os.path.join(out, "data", "tr-img.idx"), imgs)
    write_idx_labels(os.path.join(out, "data", "tr-lab.idx"), labels)
    write_idx_images(os.path.join(out, "data", "te-img.idx"), imgs[:64])
    write_idx_labels(os.path.join(out, "data", "te-lab.idx"), labels[:64])


def train(out: str) -> None:
    from cxxnet_tpu.cli import LearnTask

    conf = os.path.join(out, "smoke.conf")
    with open(conf, "w", encoding="utf-8") as f:
        f.write(CONF_TEMPLATE.format(out=out))
    rc = LearnTask().run([conf])
    if rc != 0:
        raise SystemExit(f"obs_smoke: train failed with rc={rc}")


def serve_and_scrape(out: str) -> None:
    from cxxnet_tpu import config as cfgmod
    from cxxnet_tpu.obs import alerts as obs_alerts
    from cxxnet_tpu.serve import Engine
    from cxxnet_tpu.serve.server import make_server

    with open(os.path.join(out, "smoke.conf"), "r", encoding="utf-8") as f:
        cfg = cfgmod.split_sections(cfgmod.parse_pairs(f.read()))
    engine = Engine(cfg=cfg.global_entries,
                    model_dir=os.path.join(out, "models"),
                    max_batch_size=8, batch_timeout_ms=2.0)
    httpd = make_server(engine, port=0)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    port = httpd.server_port

    def get(path: str):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=30) as r:
            ctype = r.headers.get("Content-Type", "")
            body = r.read().decode("utf-8")
        return ctype, body

    try:
        # drive the evaluator by hand for determinism (the CLI started
        # its background thread — its passes would race the fire/clear
        # assertions below)
        ev = obs_alerts.evaluator()
        ev.stop()
        # baseline evaluation BEFORE traffic: the latency rule keys on
        # the interval mean, so the next pass sees fresh observations
        ev.evaluate_once()
        rng = np.random.RandomState(1)
        for n in (1, 3, 5):
            body = json.dumps(
                {"data": rng.randn(n, 64).astype(float).tolist()}
            ).encode("utf-8")
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/predict", data=body,
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=30) as r:
                out_rows = len(json.load(r)["pred"])
                assert out_rows == n, (out_rows, n)
        # fire: requests landed since the baseline pass, mean > 0
        ev.evaluate_once()
        if ev.firing() != ["smoke_latency"]:
            raise SystemExit(
                f"obs_smoke: latency alert did not fire ({ev.firing()})")
        _, health = get("/healthz")
        h = json.loads(health)
        if h["status"] != "degraded" or "smoke_latency" not in h.get(
                "alerts", []):
            raise SystemExit(f"obs_smoke: /healthz not degraded while "
                             f"firing: {h}")
        _, alertz = get("/alertz")  # captured while firing
        ctype, text = get("/metricsz")
        assert ctype.startswith("text/plain"), ctype
        # clear: no traffic between passes -> no interval mean sample
        ev.evaluate_once()
        if ev.firing():
            raise SystemExit(
                f"obs_smoke: alert did not clear ({ev.firing()})")
        h2 = json.loads(get("/healthz")[1])
        if h2["status"] != "ok":
            raise SystemExit(f"obs_smoke: /healthz stuck degraded: {h2}")
    finally:
        httpd.shutdown()
        httpd.server_close()
        engine.close()
    # the acceptance surface: outcomes, batch fill, latency, reloads,
    # the alert gauge and the device-plane families (the in-process
    # train + the serve bucket compiles above feed them)
    for needle in ("serve_request_outcomes_total", "serve_batch_rows_total",
                   "serve_request_latency_seconds_bucket",
                   "serve_model_reloads_total", "obs_events_total",
                   "obs_alerts_firing", "xla_program_flops",
                   "xla_compile_seconds_total"):
        if needle not in text:
            raise SystemExit(f"obs_smoke: {needle!r} missing from /metricsz")
    with open(os.path.join(out, "metricsz.txt"), "w",
              encoding="utf-8") as f:
        f.write(text)
    with open(os.path.join(out, "alertz.json"), "w",
              encoding="utf-8") as f:
        f.write(alertz)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="/tmp/obs_smoke",
                    help="artifact directory (created if missing)")
    args = ap.parse_args()
    out = os.path.abspath(args.out)
    os.makedirs(out, exist_ok=True)
    for leftover in ("telemetry.jsonl", "events.jsonl", "metricsz.txt",
                     "alertz.json"):
        p = os.path.join(out, leftover)
        if os.path.exists(p):
            os.remove(p)
    make_data(out)
    train(out)
    serve_and_scrape(out)
    traces = sorted(os.listdir(os.path.join(out, "traces")))
    print(f"obs_smoke: OK — artifacts in {out}")
    print(f"  metrics:   {out}/metricsz.txt")
    print(f"  alertz:    {out}/alertz.json")
    print(f"  telemetry: {out}/telemetry.jsonl")
    print(f"  events:    {out}/events.jsonl")
    print(f"  traces:    {traces}")


if __name__ == "__main__":
    main()
