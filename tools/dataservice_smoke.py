"""DSVC=1 lane: service-fed training is bitwise-identical to local.

The data-service determinism claim (ISSUE 20), proven end to end
through the real CLI on the MNIST MLP conf:

* **parity** — a trainer whose data section is ``iter = service``
  (streaming every batch from a real ``task=data_service`` process)
  must write checkpoints with manifest CRC32s IDENTICAL to a trainer
  running the same conf on its local decode chain.  The stream is
  addressed ``(epoch, block)``, the server rewinds per epoch exactly as
  the CLI does locally, so the batch bytes — and every weight bit —
  cannot depend on where decoding runs;
* **kill/resume** — the server is SIGKILLed mid-training and a
  replacement started on the SAME port; the client reconnects,
  re-requests its cursor, and the finished run's CRCs still equal the
  local run's.  A leg that only kills after training completed is
  counted as a FAILURE (vacuous kill), not a pass;
* **shared fleet** — two trainers run concurrently against ONE server;
  both must hold bitwise parity, and the server's ``/statsz`` chunk
  cache must show ``hit_rate > 0`` (the second tenant reads decoded
  blocks from memory, which is the reason the service exists).

Usage::

    python tools/dataservice_smoke.py --out /tmp/_dsvc

Exit code: 0 when every leg holds; 1 otherwise (a hard gate, not
weather).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

NUM_ROUND = 4
BATCH = 32
N_IMAGES = 512

ENV = {
    **os.environ,
    "PYTHONPATH": REPO,
    "JAX_PLATFORMS": "cpu",
}


def _free_port() -> int:
    from cxxnet_tpu.parallel.elastic import free_port

    return free_port()


def make_data(out_dir: str) -> None:
    import numpy as np

    from cxxnet_tpu.io.mnist import write_idx_images, write_idx_labels

    rng = np.random.RandomState(0)
    imgs = rng.randint(0, 256, (N_IMAGES, 4, 4)).astype(np.uint8)
    labels = (imgs.reshape(N_IMAGES, -1).mean(1) > 127).astype(np.uint8)
    write_idx_images(os.path.join(out_dir, "img.idx"), imgs)
    write_idx_labels(os.path.join(out_dir, "lab.idx"), labels)


def make_confs(out_dir: str):
    """Two confs differing ONLY in the data section: the local decode
    chain vs ``iter = service`` (the addr rides in as a CLI override).
    Everything downstream of the batch stream is shared — that is the
    parity claim."""
    head = f"""
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 8
  init_sigma = 0.1
layer[fc1->out] = fullc:fc2
  nhidden = 2
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,16
batch_size = {BATCH}
dev = cpu
num_round = {NUM_ROUND}
eval_train = 0
eta = 0.1
momentum = 0.9
seed = 7
metric = error
silent = 1
"""
    local = os.path.join(out_dir, "local.conf")
    with open(local, "w", encoding="utf-8") as f:
        f.write(f"""
data = train
iter = mnist
  path_img = "{out_dir}/img.idx"
  path_label = "{out_dir}/lab.idx"
  shuffle = 1
iter = end
{head}""")
    service = os.path.join(out_dir, "service.conf")
    with open(service, "w", encoding="utf-8") as f:
        f.write(f"""
data = train
iter = service
iter = end
{head}""")
    return local, service


def start_server(conf: str, out_dir: str, port: int, tag: str,
                 timeout: float = 60.0):
    """Launch a real ``task=data_service`` process hosting the local
    conf's data section; returns ``(proc, ready_doc)`` once the ready
    file lands."""
    ready = os.path.join(out_dir, f"ready_{tag}_{time.time_ns()}.json")
    proc = subprocess.Popen(
        [sys.executable, "-m", "cxxnet_tpu", conf,
         "task=data_service",
         f"data_service_port={port}",
         "data_service_http_port=0",
         f"data_service_ready_file={ready}",
         "silent=1"],
        env=ENV, cwd=out_dir,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if os.path.exists(ready):
            with open(ready, "r", encoding="utf-8") as f:
                return proc, json.load(f)
        if proc.poll() is not None:
            out = proc.communicate()[0].decode()
            raise RuntimeError(
                f"data_service exited rc={proc.returncode} before "
                f"ready:\n{out[-4000:]}")
        time.sleep(0.05)
    proc.kill()
    raise RuntimeError(f"data_service not ready within {timeout}s")


def start_train(conf: str, workdir: str, overrides):
    os.makedirs(workdir, exist_ok=True)
    return subprocess.Popen(
        [sys.executable, "-m", "cxxnet_tpu", conf] + list(overrides),
        env=ENV, cwd=workdir,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )


def wait_train(proc, timeout: float) -> None:
    try:
        out = proc.communicate(timeout=timeout)[0]
    finally:
        if proc.poll() is None:
            proc.kill()
    if proc.returncode != 0:
        raise RuntimeError(f"trainer failed (rc={proc.returncode}):\n"
                           f"{out.decode()[-4000:]}")


def read_crcs(workdir: str) -> dict:
    """{round: manifest crc32} for every checkpoint a run wrote."""
    from cxxnet_tpu.utils import checkpoint as ckpt

    out = {}
    for round_, path in ckpt.list_checkpoints(
            os.path.join(workdir, "models")):
        man = ckpt.read_manifest(path)
        if man is not None:
            out[round_] = man["crc32"]
    return out


def count_ckpts(workdir: str) -> int:
    from cxxnet_tpu.utils import checkpoint as ckpt

    return len(ckpt.list_checkpoints(os.path.join(workdir, "models")))


def service_overrides(port: int):
    # retries x delay must outlast a server replacement (python + jax
    # startup), or the kill leg's client gives up before resuming
    return [f"data_service_addr=127.0.0.1:{port}",
            "data_service_retries=600",
            "data_service_retry_delay_s=0.05"]


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="/tmp/_dsvc_smoke",
                    help="scratch + verdict directory")
    ap.add_argument("--timeout", type=float, default=240.0,
                    help="per-leg wall-clock budget (seconds)")
    ap.add_argument("--json", dest="json_path", default="",
                    help="verdict path (default <out>/dataservice_"
                         "smoke.json)")
    args = ap.parse_args()

    os.makedirs(args.out, exist_ok=True)
    make_data(args.out)
    local_conf, service_conf = make_confs(args.out)
    problems = []

    # --- leg 0: the local-chain reference ------------------------------
    t0 = time.time()
    local_dir = os.path.join(args.out, "local")
    wait_train(start_train(local_conf, local_dir, []), args.timeout)
    local_s = time.time() - t0
    local_crcs = read_crcs(local_dir)
    if len(local_crcs) != NUM_ROUND + 1:
        problems.append(
            f"local run wrote {sorted(local_crcs)} rounds, expected "
            f"{NUM_ROUND + 1} checkpoints")

    # --- leg 1: service-fed parity -------------------------------------
    port = _free_port()
    srv, _ready = start_server(local_conf, args.out, port, "parity")
    t1 = time.time()
    try:
        svc_dir = os.path.join(args.out, "service")
        wait_train(start_train(service_conf, svc_dir,
                               service_overrides(port)), args.timeout)
    finally:
        srv.kill()
        srv.wait()
    service_s = time.time() - t1
    svc_crcs = read_crcs(svc_dir)
    if svc_crcs != local_crcs:
        problems.append(
            f"BITWISE PARITY FAILED: service-fed CRCs {svc_crcs} != "
            f"local CRCs {local_crcs}")

    # --- leg 2: SIGKILL the server mid-training, resume on a fresh one -
    port2 = _free_port()
    srv, _ready = start_server(local_conf, args.out, port2, "kill_a")
    t2 = time.time()
    kill_dir = os.path.join(args.out, "kill")
    trainer = start_train(service_conf, kill_dir,
                          service_overrides(port2))
    killed_at = -1
    try:
        t_poll = time.monotonic()
        while time.monotonic() - t_poll < args.timeout:
            if count_ckpts(kill_dir) >= 2 or trainer.poll() is not None:
                break
            time.sleep(0.05)
        killed_at = count_ckpts(kill_dir)
        srv.send_signal(signal.SIGKILL)
        srv.wait()
        srv2, _ready = start_server(local_conf, args.out, port2,
                                    "kill_b")
        try:
            wait_train(trainer, args.timeout)
        finally:
            srv2.kill()
            srv2.wait()
    finally:
        if trainer.poll() is None:
            trainer.kill()
        if srv.poll() is None:
            srv.kill()
    kill_s = time.time() - t2
    kill_crcs = read_crcs(kill_dir)
    if killed_at >= NUM_ROUND + 1:
        problems.append(
            f"kill leg vacuous: all {killed_at} checkpoints existed "
            "before the SIGKILL landed — nothing was resumed")
    if kill_crcs != local_crcs:
        problems.append(
            f"KILL/RESUME PARITY FAILED: post-SIGKILL CRCs {kill_crcs} "
            f"!= local CRCs {local_crcs}")

    # --- leg 3: two concurrent tenants on one server -------------------
    port3 = _free_port()
    srv, ready = start_server(local_conf, args.out, port3, "shared")
    t3 = time.time()
    hit_rate = -1.0
    try:
        tenants = [
            start_train(service_conf,
                        os.path.join(args.out, f"tenant{i}"),
                        service_overrides(port3))
            for i in range(2)
        ]
        errs = []
        for p in tenants:
            try:
                wait_train(p, args.timeout)
            except RuntimeError as e:
                errs.append(str(e))
        if errs:
            problems.append("shared-fleet trainers failed: "
                            + " | ".join(errs))
        stats = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{ready['http_port']}/statsz",
            timeout=10).read())
        hit_rate = float(stats["cache"]["hit_rate"])
    finally:
        srv.kill()
        srv.wait()
    shared_s = time.time() - t3
    for i in range(2):
        crcs = read_crcs(os.path.join(args.out, f"tenant{i}"))
        if crcs != local_crcs:
            problems.append(
                f"SHARED-FLEET PARITY FAILED: tenant{i} CRCs {crcs} != "
                f"local CRCs {local_crcs}")
    if not hit_rate > 0:
        problems.append(
            f"shared fleet cache hit_rate {hit_rate} is not > 0 — the "
            "second tenant re-decoded every block")

    doc = {
        "bench": "dataservice_smoke",
        "ts": time.time(),
        "rounds": NUM_ROUND,
        "batch": BATCH,
        "n_images": N_IMAGES,
        "crc_equal": svc_crcs == local_crcs,
        "kill_crc_equal": kill_crcs == local_crcs,
        "ckpts_at_kill": killed_at,
        "cache_hit_rate": hit_rate,
        "crcs": {str(k): f"{v:#010x}" for k, v in
                 sorted(local_crcs.items())},
        "local_wall_sec": round(local_s, 3),
        "service_wall_sec": round(service_s, 3),
        "kill_wall_sec": round(kill_s, 3),
        "shared_wall_sec": round(shared_s, 3),
        "problems": problems,
        "verdict": "ok" if not problems else "fail",
    }
    json_path = args.json_path or os.path.join(args.out,
                                               "dataservice_smoke.json")
    with open(json_path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1)
    print(json.dumps(doc, indent=1))
    for p in problems:
        print(f"FAIL {p}", file=sys.stderr)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
