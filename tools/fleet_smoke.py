"""Serving-fleet smoke: kill one of N real replicas under open-loop load.

The FLEET=1 tier-1 lane (and the ISSUE-12 acceptance): train a tiny MLP
checkpoint, launch a REAL ``task=serve replicas=N`` fleet (each replica
a full CLI subprocess with its own engine), drive sustained open-loop
burst traffic through the routing front-end, then SIGKILL one serving
replica mid-run and assert:

* **availability** — every non-shed request still succeeds: zero
  errors, zero relayed 5xx (429 shed is admission control doing its
  job, not a failure);
* **supervision** — the fleet detects the loss and restarts the dead
  replica back to healthy within ``--restart-budget`` seconds (the
  supervisor-measured wall clock lands in the verdict, and in the
  perf_guard ``fleet_bench`` history as a lower-is-better series);
* **front door** — aggregate ``/healthz`` degrades while the replica
  is down and returns to ``ok`` after the restart.

``--no-kill`` turns the same harness into the pure load story (the
ISSUE-19 acceptance): no SIGKILL, the burst runs to completion —
``--total-requests 1000000`` for the million-request proof — and the
acceptance is zero non-shed protocol errors end to end.  ``--wire
binary`` drives CXB1 frames over the pooled keep-alive client
(doc/serving.md "Binary wire protocol") instead of JSON; ``--rows``
sets rows per request and ``--progress-s`` streams running p50/p99.

Prints one JSON verdict on stdout; exit 0 on pass, 1 on fail.

Usage::

    python tools/fleet_smoke.py --out /tmp/_fleet_smoke [--replicas 3]
    python tools/fleet_smoke.py --out /tmp/_wire_burst --no-kill \
        --wire binary --total-requests 1000000 --clients 128
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

CONF = """
data = train
iter = synthetic
  nsample = 128
  input_shape = 1,1,16
  nclass = 4
iter = end

netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 16
  init_sigma = 0.1
layer[+1:a1] = relu:a1
layer[a1->out] = fullc:fc2
  nhidden = 4
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end

input_shape = 1,1,16
batch_size = 32
dev = cpu
eta = 0.1
num_round = 1
save_model = 1
eval_train = 1
metric = error
print_step = 0
model_dir = MODELDIR
"""


def _get(port, path, timeout=10):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout) as r:
        return json.loads(r.read().decode("utf-8"))


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    return env


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", required=True, help="work/artifact dir")
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--base-rate", type=float, default=40.0)
    ap.add_argument("--burst-rate", type=float, default=120.0)
    ap.add_argument("--phase", type=float, default=1.0)
    ap.add_argument("--load-before-kill", type=float, default=3.0,
                    help="seconds of load before the SIGKILL")
    ap.add_argument("--restart-budget", type=float, default=120.0,
                    help="max seconds from kill to healthy again")
    ap.add_argument("--start-timeout", type=float, default=300.0)
    ap.add_argument("--wire", default="json",
                    choices=("json", "binary"),
                    help="wire format for the load client (binary = "
                         "CXB1 frames, doc/serving.md)")
    ap.add_argument("--rows", type=int, default=1,
                    help="rows per request")
    ap.add_argument("--total-requests", type=int, default=0,
                    help="stop the burst after this many arrivals "
                         "instead of the duration window")
    ap.add_argument("--clients", type=int, default=32,
                    help="burst-driver worker pool size")
    ap.add_argument("--progress-s", type=float, default=0.0,
                    help="stream running burst counts + p50/p99 to "
                         "stderr every N seconds (0 = off)")
    ap.add_argument("--no-kill", action="store_true",
                    help="pure load story: run the burst to completion "
                         "with no replica SIGKILL (the >= 10^6-request "
                         "acceptance)")
    ap.add_argument("--burst-timeout", type=float, default=3600.0,
                    help="--no-kill: max seconds to wait for the burst")
    args = ap.parse_args(argv)

    os.makedirs(args.out, exist_ok=True)
    model_dir = os.path.join(args.out, "models")
    conf_path = os.path.join(args.out, "fleet_smoke.conf")
    with open(conf_path, "w", encoding="utf-8") as f:
        f.write(CONF.replace("MODELDIR", model_dir))

    # 1. train one round so the replicas have a checkpoint to serve
    r = subprocess.run(
        [sys.executable, "-m", "cxxnet_tpu", conf_path, "silent=1"],
        capture_output=True, text=True, cwd=args.out, env=_env(),
        timeout=300)
    if r.returncode != 0:
        print(json.dumps({"ok": False, "stage": "train",
                          "error": r.stderr[-2000:]}))
        return 1

    # 2. launch the fleet on an ephemeral port
    fleet_cmd = [
        sys.executable, "-m", "cxxnet_tpu", conf_path,
        "task=serve", f"replicas={args.replicas}", "serve_port=0",
        "silent=1", "batch_timeout_ms=1",
        "fleet_probe_period_s=0.25", "fleet_probe_timeout_s=2",
        "fleet_restart_backoff_s=0.5",
        f"fleet_log_dir={os.path.join(args.out, 'fleet_logs')}",
    ]
    proc = subprocess.Popen(fleet_cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True,
                            cwd=args.out, env=_env())
    lines: list = []
    threading.Thread(
        target=lambda: [lines.append(l) for l in proc.stdout],
        daemon=True).start()

    verdict = {"ok": False, "replicas": args.replicas}
    try:
        # wait for the front door + full rotation
        port = None
        deadline = time.time() + args.start_timeout
        while time.time() < deadline and port is None:
            for line in list(lines):
                if line.startswith("fleet: serving") and "http://" in line:
                    port = int(line.rsplit(":", 1)[1])
                    break
            if proc.poll() is not None:
                raise RuntimeError("fleet died:\n" + "".join(lines))
            time.sleep(0.2)
        if port is None:
            raise RuntimeError("fleet never reported its port:\n"
                               + "".join(lines)[-2000:])
        h = None
        while time.time() < deadline:
            h = _get(port, "/healthz")
            if h["replicas"]["healthy"] == args.replicas:
                break
            time.sleep(0.5)
        else:
            raise RuntimeError(f"not all replicas healthy: {h}")

        # 3. sustained open-loop burst load through the front door
        import numpy as np
        import serve_bench

        x = np.full((args.rows, 16), 0.5, np.float32)
        verdict["wire"] = args.wire
        fire = serve_bench.make_url_fire(f"http://127.0.0.1:{port}", x,
                                         wire_fmt=args.wire)
        burst_box = {}

        def _load():
            burst_box["burst"] = serve_bench.open_loop_burst(
                fire, args.base_rate, args.burst_rate, args.phase,
                duration_s=args.load_before_kill + args.restart_budget,
                total_requests=args.total_requests,
                clients=args.clients, progress_s=args.progress_s)

        load_thread = threading.Thread(target=_load, daemon=True)
        load_thread.start()

        restart_wall = None
        if args.no_kill:
            # the pure load story: let the burst run to completion
            load_thread.join(timeout=args.burst_timeout)
            if load_thread.is_alive():
                raise RuntimeError(
                    f"burst still running after {args.burst_timeout:g}s")
        else:
            time.sleep(args.load_before_kill)

            # 4. SIGKILL one serving replica mid-load
            st = _get(port, "/statsz")
            victim = next(rep for rep in st["replicas"]
                          if rep["role"] == "serve"
                          and rep["state"] == "healthy" and rep["pid"])
            os.kill(victim["pid"], signal.SIGKILL)
            t_kill = time.monotonic()
            verdict["killed"] = {"idx": victim["idx"],
                                 "pid": victim["pid"]}

            # 5. wait for detection + restart back to full rotation
            degraded_seen = False
            while time.monotonic() - t_kill < args.restart_budget:
                h = _get(port, "/healthz")
                if h["status"] != "ok":
                    degraded_seen = True
                if (degraded_seen
                        and h["replicas"]["healthy"] == args.replicas):
                    st = _get(port, "/statsz")
                    restart_wall = st["last_restart_wall_s"]
                    break
                time.sleep(0.25)
            if restart_wall is None:
                raise RuntimeError(
                    f"replica not restarted within "
                    f"{args.restart_budget:g}s "
                    f"(degraded_seen={degraded_seen})")
            verdict["restart_wall_s"] = restart_wall
            verdict["kill_to_healthy_s"] = time.monotonic() - t_kill
            verdict["degraded_seen"] = degraded_seen
            load_thread.join(timeout=args.restart_budget + 60)

        burst = burst_box.get("burst") or {}
        verdict["burst"] = burst
        st = _get(port, "/statsz")
        verdict["router"] = {k: st[k] for k in
                             ("requests", "shed", "failovers",
                              "relayed_5xx", "unroutable", "expired")}
        verdict["restarts_total"] = st["restarts_total"]
        lat = burst.get("latency_ms", {})
        print(f"bench[fleet_burst:{args.wire}] sent "
              f"{burst.get('sent', 0)} ok {burst.get('completed', 0)} "
              f"shed {burst.get('shed', 0)} "
              f"expired {burst.get('expired', 0)} "
              f"err {burst.get('errors', 1)} "
              f"achieved {burst.get('achieved_req_per_sec', 0.0):.1f} "
              f"req/s p50 {lat.get('p50', float('nan')):.2f} ms "
              f"p99 {lat.get('p99', float('nan')):.2f} ms",
              file=sys.stderr, flush=True)

        # 6. the acceptance: zero non-shed failures (and, in the kill
        # variant, a restart inside the budget)
        problems = []
        if burst.get("errors", 1) != 0:
            problems.append(f"burst errors {burst.get('errors')}")
        if burst.get("expired", 0) != 0:
            problems.append(f"burst expired {burst.get('expired')}")
        if st["relayed_5xx"] != 0:
            problems.append(f"relayed_5xx {st['relayed_5xx']}")
        if st["unroutable"] != 0:
            problems.append(f"unroutable {st['unroutable']}")
        if not args.no_kill:
            if restart_wall > args.restart_budget:
                problems.append(f"restart_wall_s {restart_wall:.1f} > "
                                f"budget {args.restart_budget:g}")
            if st["restarts_total"] < 1:
                problems.append("no restart recorded")
        verdict["problems"] = problems
        verdict["ok"] = not problems
    except Exception as e:  # noqa: BLE001 - verdict carries the failure
        verdict["error"] = f"{type(e).__name__}: {e}"
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)
        verdict["fleet_exit_code"] = proc.returncode

    if verdict["ok"] and verdict.get("fleet_exit_code") != 0:
        verdict["ok"] = False
        verdict.setdefault("problems", []).append(
            f"fleet exit code {verdict['fleet_exit_code']}")
    line = json.dumps(verdict, indent=1)
    print(line)
    with open(os.path.join(args.out, "fleet_smoke.json"), "w",
              encoding="utf-8") as f:
        f.write(line + "\n")
    if not verdict["ok"]:
        tail = "".join(lines)[-3000:]
        print(f"fleet_smoke FAILED; fleet output tail:\n{tail}",
              file=sys.stderr)
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
