"""Optimized-HLO inspector: is the conv epilogue (bias/relu/BN scale)
fused, and what does the compiler actually schedule?

VERDICT r3 #3 asked for "conv+bias+relu epilogue fusion checks in the
HLO" as part of the conv-efficiency attack.  This compiles a model's
REAL fused train step (the same ``NetTrainer._fused_step_fn`` program
``bench.py`` times), dumps the optimized module, and summarizes:

* how many ``convolution``/``dot`` ops survive (algebraic fusions like
  the sibling-1x1 concat rewrite reduce the count);
* how many live *inside* fusion computations vs standalone — on TPU a
  standalone conv with a separate elementwise kernel after it means an
  extra HBM round-trip of the activation;
* the op-category histogram of the entry computation (what the step
  actually dispatches).

Usage (CPU works for structure; run on TPU for the real backend's
fusion decisions):

    python tools/hlo_inspect.py [googlenet|resnet|vgg|alexnet] [batch] [k=v ...]

Trailing ``k=v`` pairs are appended to the conf — e.g.
``conv_branch_embed=1`` shows the branch-embedding rewrite collapsing
the 18 inception branch convs into 9 block-kernel convs (compare the
convolution/dot count against the base run).
"""

import collections
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_trainer(model: str, batch: int, overrides=()):
    from cxxnet_tpu import config as cfgmod
    from cxxnet_tpu.models import (alexnet_conf, googlenet_conf,
                                   resnet50_conf, vgg16_conf)
    from cxxnet_tpu.nnet.trainer import NetTrainer

    conf = {
        "googlenet": googlenet_conf,
        "resnet": resnet50_conf,
        "vgg": vgg16_conf,
        "alexnet": alexnet_conf,
    }[model](batch_size=batch, synthetic=False, dev="tpu")
    conf += "".join(f"{k} = {v}\n" for k, v in overrides)
    tr = NetTrainer()
    tr.set_params(cfgmod.parse_pairs(conf))
    tr.eval_train = 0
    tr.init_model()
    return tr


def optimized_hlo(tr, batch: int, input_size: int) -> str:
    import jax.numpy as jnp
    import numpy as np

    from cxxnet_tpu.io.data import DataBatch

    data = np.zeros((batch, input_size, input_size, 3), np.float32)
    labels = np.zeros((batch, 1), np.float32)
    # the same fused fwd+bwd+update program update()/update_scan run,
    # assembled the way update() does — compiled, not executed
    d, l, extras, mask, _ = tr._pad_train_batch(
        DataBatch(data=data, label=labels)
    )
    args = (
        tr.params, tr.ustates, tr.aux,
        tr._to_device(d), tr._to_device(l), tr._to_device(mask),
        tr._next_rng(), jnp.asarray(0, jnp.int32),
        tuple(tr._to_device(e) for e in extras),
    )
    return tr._fused_step_fn().lower(*args).compile().as_text()


def summarize(hlo: str) -> None:
    convs = re.findall(r"^\s*(?:ROOT\s+)?%?[\w.-]+ = [^=]*"
                       r"(convolution|dot)\(", hlo, re.M)
    n_conv = len(convs)
    in_fusion = 0
    standalone = 0
    # fusion computations are named %fused_computation.* / %wide.*; ops
    # listed inside those computation bodies are fused
    cur_fused = False
    cat = collections.Counter()
    for line in hlo.splitlines():
        # computation headers look like either
        #   %fused_computation.12 (param0: f32[...]) -> f32[...] {
        #   ENTRY %main.345 (args: ...) -> (...) {
        m = re.match(r"^\s*(ENTRY\s+)?%?([\w.-]+)\s*\(", line)
        if m and line.rstrip().endswith("{"):
            cur_fused = (m.group(1) is None) and "fused" in m.group(2)
        if re.search(r"= [^=]*\b(convolution|dot)\(", line):
            if cur_fused:
                in_fusion += 1
            else:
                standalone += 1
        m2 = re.search(r"= \S+ (\w+)\(", line)
        if m2 and "ENTRY" not in line:
            cat[m2.group(1)] += 1
    print(f"convolution/dot ops: {n_conv} "
          f"({in_fusion} inside fusions, {standalone} standalone)")
    top = ", ".join(f"{k}:{v}" for k, v in cat.most_common(14))
    print(f"op histogram: {top}")
    # the epilogue check: a standalone broadcast-add or max right after
    # a conv means bias/relu did NOT fuse into the conv's consumer
    bare_eltwise = len(re.findall(
        r"^\s*%?[\w.-]+ = \S+ (?:add|maximum)\([^)]*convolution",
        hlo, re.M))
    print(f"bias/relu consuming a conv OUTSIDE a fusion: {bare_eltwise} "
          "(0 = every conv epilogue fused)")


def main() -> None:
    args = sys.argv[1:]
    overrides = [tuple(a.split("=", 1)) for a in args if "=" in a]
    args = [a for a in args if "=" not in a]
    model = args[0] if args else "googlenet"
    batch = int(args[1]) if len(args) > 1 else 16
    size = 227 if model == "alexnet" else 224
    tr = build_trainer(model, batch, overrides)
    hlo = optimized_hlo(tr, batch, size)
    out = f"/tmp/hlo_{model}.txt"
    with open(out, "w") as f:
        f.write(hlo)
    print(f"# optimized HLO -> {out} ({len(hlo.splitlines())} lines)")
    summarize(hlo)


if __name__ == "__main__":
    main()
