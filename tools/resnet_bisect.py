"""ResNet-50 step-time bisection (doc/performance.md discipline).

Times the scanned train step for diagnostic variants of the conf,
isolating cost centers the way the GoogLeNet pooling/fusion bisection
did.  Run on the TPU host:

    python tools/resnet_bisect.py [variant ...]

Variants: base, onepass, nobn, noavg, nomaxpool, stems2d (default: all).
"""

import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _sub(conf: str, old: str, new: str) -> str:
    """str.replace that refuses to silently no-op: a drifted builder
    string would otherwise turn an A/B variant into base-vs-base."""
    out = conf.replace(old, new)
    assert out != conf or old == new, f"conf drift: {old!r} not found"
    return out


def variant_conf(name: str, batch: int) -> str:
    from cxxnet_tpu.models import resnet50_conf

    conf = resnet50_conf(batch_size=batch, input_size=224, synthetic=False,
                         dev="tpu")
    # resnet50_conf now emits a global `bn_stats = onepass` (the measured
    # default); the bisect's base/onepass A/B isolates the statistics
    # form, so "base" must restore the twopass control
    conf = _sub(conf, "bn_stats = onepass\n", "bn_stats = twopass\n")
    if name == "base":
        return conf
    if name == "onepass":
        # every batch_norm computes E[x^2]-E[x]^2 in one pass
        out = re.sub(r"(= batch_norm:\w+\n)", r"\1  bn_stats = onepass\n",
                     conf)
        assert out != conf, "conf drift: no batch_norm layers matched"
        return out
    if name == "nobn":
        # batch_norm -> relu (fuses into the conv epilogue, ~free):
        # isolates what all 53 BNs cost
        out = re.sub(r"= batch_norm:\w+\n", "= relu\n", conf)
        assert out != conf, "conf drift: no batch_norm layers matched"
        return out
    if name == "noavg":
        # global avg pool -> stride-7 max slice (cheap): isolates tail
        return _sub(conf,
            "layer[s3b2->pool] = avg_pooling\n  kernel_size = 7\n"
            "  stride = 1\n",
            "layer[s3b2->pool] = max_pooling\n  kernel_size = 1\n"
            "  stride = 7\n",
        )
    if name == "nomaxpool":
        # stem max_pool k3 s2 -> avg (GoogLeNet diag analog)
        return _sub(conf,
            "layer[b1->p1] = max_pooling\n  kernel_size = 3\n  stride = 2\n",
            "layer[b1->p1] = avg_pooling\n  kernel_size = 3\n  stride = 2\n",
        )
    if name == "stems2d":
        # the 7x7 s2 stem via space-to-depth (conv._conv_s2d A/B)
        out = _sub(conf,
            "layer[0->c1] = conv:conv1\n",
            "layer[0->c1] = conv:conv1\n  conv_s2d = 1\n",
        )
        return out
    if name == "wino":
        # every 3x3 s1 conv via Winograd F(4x4,3x3) (layers/conv.py)
        return conf + "conv_wino = 1\n"
    raise SystemExit(f"unknown variant {name}")


if __name__ == "__main__":
    from bisect_common import run_bisect

    run_bisect(variant_conf,
               ["base", "onepass", "nobn", "noavg", "nomaxpool",
                "stems2d", "wino"],
               scan_k=30)
