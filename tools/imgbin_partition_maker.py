#!/usr/bin/env python
"""Split an image list into size-bounded shards and pack each to a CXBP bin.

Parity: ``/root/reference/tools/imgbin-partition-maker.py`` (emits a
Makefile of ``im2bin`` invocations, partitions bounded by cumulative file
size, optional shuffle with a fixed seed).  This version can also pack
directly (``--pack``), since the packer is in-process Python here; the
resulting ``prefix_NNN.{lst,bin}`` pairs are what the ``imgbin`` iterator's
``image_bin``/``image_list`` multi-shard config consumes (one shard per
distributed worker, ``iter_thread_imbin_x-inl.hpp:108-139`` semantics).

Usage:
    python tools/imgbin_partition_maker.py --img_list all.lst \
        --img_root /data/images --prefix train --out ./shards \
        --partition_size 256 --shuffle 1 [--pack | --makefile Gen.mk]
"""

from __future__ import annotations

import argparse
import os
import random
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def split_partitions(lines, img_root, max_bytes):
    """Greedy split by cumulative image file size (reference rule:
    start a new partition when adding ~10KB headroom would overflow)."""
    parts = []
    cur, sz = [], 0
    for line in lines:
        fname = line.rstrip("\n").split("\t")[-1]
        path = os.path.join(img_root, fname)
        fsz = os.path.getsize(path) if os.path.exists(path) else 10240
        if cur and sz + 10240 > max_bytes:
            parts.append(cur)
            cur, sz = [], 0
        cur.append(line)
        sz += fsz
    if cur:
        parts.append(cur)
    return parts


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--img_list", required=True)
    ap.add_argument("--img_root", required=True)
    ap.add_argument("--prefix", required=True)
    ap.add_argument("--out", required=True)
    ap.add_argument("--partition_size", default="256",
                    help="max size of one bin, MB")
    ap.add_argument("--shuffle", default="0")
    ap.add_argument("--pack", action="store_true",
                    help="pack shards now instead of emitting a Makefile")
    ap.add_argument("--makefile", default="Gen.mk")
    ap.add_argument("--im2bin", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "im2bin.py"))
    args = ap.parse_args(argv)

    random.seed(888)  # reference's fixed shuffle seed
    with open(args.img_list, "r", encoding="utf-8") as f:
        lst = [line for line in f if line.strip()]
    if args.shuffle == "1":
        random.shuffle(lst)

    missing = [
        line.rstrip("\n").split("\t")[-1]
        for line in lst
        if not os.path.exists(
            os.path.join(args.img_root, line.rstrip("\n").split("\t")[-1])
        )
    ]
    if missing and args.pack:
        # fail before writing anything rather than leaving partial shards
        raise SystemExit(
            f"{len(missing)} listed images missing under {args.img_root} "
            f"(first: {missing[0]})"
        )
    os.makedirs(args.out, exist_ok=True)
    parts = split_partitions(
        lst, args.img_root, int(args.partition_size) << 20
    )
    lst_bin = []
    for i, part in enumerate(parts, start=1):
        lst_path = os.path.join(args.out, f"{args.prefix}_{i:03d}.lst")
        bin_path = os.path.join(args.out, f"{args.prefix}_{i:03d}.bin")
        with open(lst_path, "w", encoding="utf-8") as f:
            f.writelines(part)
        lst_bin.append((lst_path, bin_path))

    if args.pack:
        from cxxnet_tpu.io.imgbin import BinPageWriter, parse_lst_line

        for lst_path, bin_path in lst_bin:
            writer = BinPageWriter(bin_path)
            with open(lst_path, "r", encoding="utf-8") as f:
                for line in f:
                    if not line.strip():
                        continue
                    _, _, fname = parse_lst_line(line)
                    with open(os.path.join(args.img_root, fname), "rb") as im:
                        writer.push(im.read())
            writer.close()
    else:
        with open(args.makefile, "w", encoding="utf-8") as mk:
            objs = " ".join(b for _, b in lst_bin)
            mk.write(f"all: {objs}\n\n")
            for lst_path, bin_path in lst_bin:
                mk.write(
                    f"{bin_path}: {lst_path}\n\tpython {args.im2bin} "
                    f"{lst_path} {args.img_root} {bin_path}\n\n"
                )
    print(f"{len(parts)} partitions -> {args.out}", file=sys.stderr)
    for lst_path, bin_path in lst_bin:
        print(f"{lst_path}\t{bin_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
