#!/usr/bin/env python3
"""Crash-consistency auditor: replay every crash point, run real recovery.

The CRASH=1 tier-1 lane (doc/robustness.md "Crash-consistency
contract").  Four recorded workloads exercise every durable writer —
checkpoint + manifest, publish pointer, feedback log + ``.commit``
sidecars + cursor, retention compaction — under
``cxxnet_tpu.utils.diskio.recording``.  For every prefix of the
recorded op journal the simulator computes the post-crash filesystem
under the ext4-reorder model (``flush`` / ``sync`` / ``torn`` variants,
torn tails cut at several byte counts), materializes it into a fresh
directory, runs the REAL recovery paths (``find_latest_valid``,
``read_publish_pointer``, ``FeedbackWriter`` reopen + append,
``FeedbackReader.read_since``, ``Sweeper.sweep``), and asserts the
invariants the marks in the journal acknowledged before the crash:

* the publish pointer never names a missing or CRC-invalid round;
* a feedback record acknowledged as committed is never lost, an
  acknowledged lineage id is never reused, and a torn page never
  surfaces;
* the retention boundary never strands a live cursor, and consumed
  records never reappear behind it;
* checkpoint resume is monotonic — never backward past a torn file —
  and every ``NNNN.model`` that surfaces validates.

A named regression corpus pins previously-found bugs as hand-built
states (e.g. ``torn-commit-sidecar-append`` — a torn sidecar line that
would fuse with the next commit entry and hide every later commit).

Exit 0 with verdict "ok" only when every explored state passes and at
least ``--min-states`` distinct states were covered.  ``--out`` writes
the verdict JSON that ``tools/perf_guard.py --bench crash_audit``
tracks (states_explored, violations, wall_s).
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import shutil
import struct
import sys
import tempfile
import time
from typing import Callable, Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

from cxxnet_tpu.loop import feedback_log as fl  # noqa: E402
from cxxnet_tpu.loop import retention as rt  # noqa: E402
from cxxnet_tpu.utils import checkpoint as ck  # noqa: E402
from cxxnet_tpu.utils import diskio, faults  # noqa: E402

REC_SHAPE = (1, 1, 4)  # tiny but real (H, W, C) feedback payload


def _rec_data(val: float) -> np.ndarray:
    return np.full(REC_SHAPE, np.float32(val))


def _model_blob(round_: int) -> bytes:
    """A structurally valid model payload (magic + header), so a crash
    state that kept the checkpoint but lost its manifest still passes
    ``validate_checkpoint``'s structural fallback — exactly like a real
    legacy checkpoint would."""
    hdr = json.dumps({"round": round_, "audit": True}).encode("utf-8")
    payload = hashlib.sha256(b"payload-%d" % round_).digest() * 8
    return ck.MODEL_MAGIC + struct.pack("<I", len(hdr)) + hdr + payload


# ----------------------------------------------------------------------
# workloads: recorded op journals with invariant marks


def wl_checkpoint(root: str) -> dict:
    """Six checkpoint rounds with a mid-stream and a final retention
    pass (keep_latest=3)."""
    mdir = os.path.join(root, "models")
    for r in range(1, 7):
        ck.write_checkpoint(ck.publish_path(mdir, r), _model_blob(r),
                            round_=r)
        diskio.mark("ckpt_durable", round=r)
        if r == 5:
            removed = ck.apply_retention(mdir, keep_latest=3)
            diskio.mark("ckpt_retention", keep=3, removed=len(removed))
    removed = ck.apply_retention(mdir, keep_latest=3)
    diskio.mark("ckpt_retention", keep=3, removed=len(removed))
    return {}


def wl_publish(root: str) -> dict:
    """Checkpoint rounds + three publish-pointer flips + retention that
    prunes superseded rounds (keeps every round the pointer could still
    name)."""
    mdir = os.path.join(root, "models")
    for r in range(1, 5):
        ck.write_checkpoint(ck.publish_path(mdir, r), _model_blob(r),
                            round_=r)
        diskio.mark("ckpt_durable", round=r)
    prev = None
    for r in (2, 3, 4):
        # the pointer stores the round-relative name so the audited
        # state stays relocatable (the real publisher stores the path
        # it wrote, which is equivalent inside one model dir)
        ck.write_publish_pointer(mdir, r, f"{r:04d}.model",
                                 prev_round=prev)
        diskio.mark("published", round=r)
        prev = r
    ck.apply_retention(mdir, keep_latest=3)
    diskio.mark("ckpt_retention", keep=3, removed=1)
    return {}


def wl_feedback(root: str) -> dict:
    """Feedback appends with explicit page commits, a rotation, and a
    mid-workload clean close + reopen."""
    fdir = os.path.join(root, "fb")
    val = [1000.0]

    def _append(w, n):
        seqs, vals = [], []
        for _ in range(n):
            val[0] += 1.0
            s = w.append_seq(_rec_data(val[0]), [val[0]])
            diskio.mark("acked", seq=s, val=val[0])
            seqs.append(s)
            vals.append(val[0])
        return seqs, vals

    w = fl.FeedbackWriter(fdir, page_bytes=1 << 20, rotate_bytes=200,
                          fsync=True, drop_on_error=False)
    for _ in range(2):
        seqs, vals = _append(w, 3)
        w.flush()
        diskio.mark("committed", seqs=seqs, vals=vals)
    w.close()
    # clean reopen mid-history: resume must continue the lineage
    w = fl.FeedbackWriter(fdir, page_bytes=1 << 20, rotate_bytes=200,
                          fsync=True, drop_on_error=False)
    seqs, vals = _append(w, 2)
    w.flush()
    diskio.mark("committed", seqs=seqs, vals=vals)
    w.close()
    return {}


def wl_retention(root: str) -> dict:
    """Append / consume / sweep cycles: every flush rotates the shard
    (rotate_bytes=1), the cursor is persisted after each consume, and an
    aggressive sweep (retain_shards=0) compacts consumed shards."""
    fdir = os.path.join(root, "feedback")
    cpath = os.path.join(root, "state", "cursor.json")
    cf = fl.CursorFile(cpath)
    rdr = fl.FeedbackReader(fdir)
    sw = rt.Sweeper(fdir, rt.RetentionOptions(retain_shards=0))
    cursor_history: List[dict] = []
    val = [2000.0]
    w = fl.FeedbackWriter(fdir, page_bytes=1 << 20, rotate_bytes=1,
                          fsync=True, drop_on_error=False)
    for _cycle in range(3):
        for _page in range(2):
            seqs, vals = [], []
            for _ in range(2):
                val[0] += 1.0
                s = w.append_seq(_rec_data(val[0]), [val[0]])
                diskio.mark("acked", seq=s, val=val[0])
                seqs.append(s)
                vals.append(val[0])
            w.flush()
            diskio.mark("committed", seqs=seqs, vals=vals)
        recs, cur = rdr.read_since(cf.load())
        consumed = max((r.seq for r in recs if r.seq is not None),
                       default=-1)
        cf.store(cur)
        hist = {"shard": int(cur["shard"]), "off": int(cur["off"]),
                "consumed_through": int(consumed)}
        cursor_history.append(hist)
        diskio.mark("cursor", **hist)
        out = sw.sweep(cur)
        diskio.mark("swept", below=out["compacted_below"])
    w.close()
    return {"cursor_history": cursor_history}


# ----------------------------------------------------------------------
# invariant checkers: run REAL recovery code against a recovered tree


def _marked(marks: List[dict], name: str) -> List[dict]:
    return [m for m in marks if m["name"] == name]


def check_checkpoint(out_dir: str, marks: List[dict], ctx: dict,
                     sub: str = "models") -> List[str]:
    mdir = os.path.join(out_dir, sub)
    vio: List[str] = []
    for r, path in ck.list_checkpoints(mdir):
        reason = ck.validate_checkpoint(path)
        if reason is not None:
            vio.append(f"checkpoint {r:04d}.model surfaced invalid: "
                       f"{reason}")
    durable = [m["round"] for m in _marked(marks, "ckpt_durable")]
    if durable:
        latest = ck.find_latest_valid(mdir, silent=True)
        if latest is None:
            vio.append(f"no valid checkpoint recoverable though round "
                       f"{max(durable)} was acknowledged durable")
        elif latest[0] < max(durable):
            vio.append(f"resume went backward: latest valid round "
                       f"{latest[0]} < acknowledged {max(durable)}")
    return vio


def check_publish(out_dir: str, marks: List[dict], ctx: dict) -> List[str]:
    mdir = os.path.join(out_dir, "models")
    vio = check_checkpoint(out_dir, marks, ctx)
    ptr = ck.read_publish_pointer(mdir)
    published = [m["round"] for m in _marked(marks, "published")]
    if published:
        if ptr is None:
            vio.append(f"publish pointer lost though round "
                       f"{max(published)} was acknowledged published")
        elif int(ptr["round"]) < max(published):
            vio.append(f"publish pointer rolled back: names round "
                       f"{ptr['round']} < acknowledged {max(published)}")
    if ptr is not None:
        path = ptr["path"]
        full = path if os.path.isabs(path) else os.path.join(mdir, path)
        if not os.path.exists(full):
            vio.append(f"publish pointer names missing checkpoint "
                       f"{ptr['path']} (round {ptr['round']})")
        else:
            reason = ck.validate_checkpoint(full)
            if reason is not None:
                vio.append(f"publish pointer names invalid checkpoint "
                           f"round {ptr['round']}: {reason}")
    return vio


def _committed_map(marks: List[dict]) -> Dict[int, float]:
    out: Dict[int, float] = {}
    for m in _marked(marks, "committed"):
        for s, v in zip(m["seqs"], m["vals"]):
            out[int(s)] = float(v)
    return out


def _read_all(fdir: str, cursor: Optional[dict] = None):
    recs, cur = fl.FeedbackReader(fdir).read_since(cursor)
    return {int(r.seq): float(r.labels[0])
            for r in recs if r.seq is not None}, cur


def check_feedback(out_dir: str, marks: List[dict], ctx: dict) -> List[str]:
    fdir = os.path.join(out_dir, "fb")
    vio: List[str] = []
    committed = _committed_map(marks)
    acked = {int(m["seq"]) for m in _marked(marks, "acked")
             if m["seq"] is not None}
    # real recovery: reopen the writer (torn-tail + torn-sidecar
    # truncation), then prove the log still accepts and commits
    w = fl.FeedbackWriter(fdir, page_bytes=1 << 20, rotate_bytes=200,
                          fsync=True, drop_on_error=False)
    new_seqs = []
    for i in range(2):
        s = w.append_seq(_rec_data(-1.0), [-1.0])
        if s is None:
            vio.append("post-recovery append was dropped")
        else:
            new_seqs.append(int(s))
    w.flush()
    w.close()
    if set(new_seqs) & acked:
        vio.append(f"acknowledged lineage ids reused after crash: "
                   f"{sorted(set(new_seqs) & acked)}")
    got, _cur = _read_all(fdir)
    for s in sorted(committed):
        if s not in got:
            vio.append(f"committed seq {s} lost after recovery")
        elif got[s] != committed[s]:
            vio.append(f"committed seq {s} content mismatch: "
                       f"{got[s]} != {committed[s]} (torn page surfaced)")
    for s in new_seqs:
        if s not in got:
            vio.append(f"post-recovery commit invisible (seq {s}): "
                       "torn sidecar fused with the new entry")
    return vio


def check_retention(out_dir: str, marks: List[dict], ctx: dict) -> List[str]:
    fdir = os.path.join(out_dir, "feedback")
    cpath = os.path.join(out_dir, "state", "cursor.json")
    vio: List[str] = []
    committed = _committed_map(marks)
    cur = fl.CursorFile(cpath).load()
    try:
        got, _ = _read_all(fdir, dict(cur))
    except fl.StaleCursorError as e:
        return [f"retention stranded a live cursor: {e}"]
    # which consume the recovered cursor corresponds to: the durable
    # cursor is always one the workload actually stored (atomic write),
    # or the {0,0} default when no store survived
    consumed_through = -1
    for h in ctx.get("cursor_history", []):
        if h["shard"] == cur["shard"] and h["off"] == cur["off"]:
            consumed_through = h["consumed_through"]
    if (cur["shard"], cur["off"]) != (0, 0) and consumed_through < 0 \
            and ctx.get("cursor_history"):
        vio.append(f"recovered cursor {cur} matches no acknowledged "
                   "store (torn cursor file)")
    required = {s for s in committed if s > consumed_through}
    for s in sorted(required):
        if s not in got:
            vio.append(f"unconsumed committed seq {s} unreadable "
                       f"past cursor {cur}")
    stale = {s for s in got if s <= consumed_through}
    if stale:
        vio.append(f"consumed records reappeared past the cursor: "
                   f"{sorted(stale)}")
    # a re-sweep over the recovered state must be idempotent: orphans
    # below the boundary go, nothing the cursor still needs does
    try:
        rt.Sweeper(fdir, rt.RetentionOptions(retain_shards=0)).sweep(cur)
    except Exception as e:  # noqa: BLE001 - any raise is a violation
        return vio + [f"re-sweep after crash raised "
                      f"{type(e).__name__}: {e}"]
    got2, _ = _read_all(fdir, dict(cur))
    for s in sorted(required):
        if s not in got2:
            vio.append(f"re-sweep deleted unconsumed committed seq {s}")
    return vio


WORKLOADS: List[Tuple[str, Callable, Callable]] = [
    ("checkpoint", wl_checkpoint, check_checkpoint),
    ("publish", wl_publish, check_publish),
    ("feedback", wl_feedback, check_feedback),
    ("retention", wl_retention, check_retention),
]


# ----------------------------------------------------------------------
# enumeration


def _unsynced_tail_len(ops: List[dict], k: int) -> Optional[int]:
    """Length of the write the ``torn`` variant would cut at crash
    point ``k`` (None when every write is covered by a later fsync —
    an fsync-acknowledged write can never tear)."""
    for i in range(k - 1, -1, -1):
        op = ops[i]
        if op["op"] != "write" or op.get("snap"):
            continue
        for j in range(i + 1, k):
            oj = ops[j]
            if oj["op"] == "fsync" and oj.get("fid") == op["fid"]:
                return None
        return len(op["data"])
    return None


def _marks_digest(marks: List[dict]) -> str:
    return hashlib.sha1(
        json.dumps(marks, sort_keys=True).encode("utf-8")).hexdigest()


def audit_workload(name: str, workload: Callable, checker: Callable,
                   scratch: str, stride: int,
                   torn_keeps: int) -> dict:
    rec_root = tempfile.mkdtemp(prefix=f"rec-{name}-", dir=scratch)
    with diskio.recording(rec_root) as rec:
        ctx = workload(rec_root) or {}
    ops = list(rec.ops)
    shutil.rmtree(rec_root, ignore_errors=True)

    seen: Dict[str, Tuple[int, str]] = {}
    explored = 0
    violations: List[dict] = []

    def _state(k: int, variant: str, keep: Optional[int]) -> None:
        nonlocal explored
        tree = diskio.simulate_crash(ops, k, variant, torn_keep=keep)
        if tree is None:
            return
        explored += 1
        marks = diskio.marks_before(ops, k)
        key = diskio.tree_fingerprint(tree) + _marks_digest(marks)
        if key in seen:
            return
        seen[key] = (k, variant)
        out_dir = tempfile.mkdtemp(prefix=f"state-{name}-", dir=scratch)
        try:
            diskio.write_tree(tree, out_dir)
            try:
                msgs = checker(out_dir, marks, ctx)
            except Exception as e:  # noqa: BLE001 - recovery must not raise
                msgs = [f"recovery raised {type(e).__name__}: {e}"]
            for msg in msgs:
                violations.append({"workload": name, "k": k,
                                   "variant": variant, "keep": keep,
                                   "msg": msg})
        finally:
            shutil.rmtree(out_dir, ignore_errors=True)

    for k in range(0, len(ops) + 1, max(1, stride)):
        for variant in ("flush", "sync"):
            _state(k, variant, None)
        tail = _unsynced_tail_len(ops, k)
        if tail is not None and tail > 1:
            keeps = {1, tail - 1}
            if torn_keeps >= 3:
                keeps.add(tail // 2)
            for keep in sorted(keeps):
                if 0 < keep < tail:
                    _state(k, "torn", keep)

    return {"ops": len(ops), "explored": explored,
            "distinct": len(seen), "violations": violations}


# ----------------------------------------------------------------------
# named regression corpus: hand-built states pinning found bugs


def reg_torn_commit_sidecar_append(scratch: str) -> List[str]:
    """A torn trailing ``.commit`` line must be truncated on reopen —
    otherwise the next commit entry fuses onto it, parsing stops at the
    fused line, and every commit after it silently vanishes (the
    satellite-6 bug this audit found)."""
    d = tempfile.mkdtemp(prefix="reg-sidecar-", dir=scratch)
    try:
        w = fl.FeedbackWriter(d, page_bytes=1 << 20, rotate_bytes=1 << 20,
                              fsync=True, drop_on_error=False)
        s1 = w.append_seq(_rec_data(1.0), [1.0])
        w.flush()
        s2 = w.append_seq(_rec_data(2.0), [2.0])
        w.flush()
        w.close()
        cpath = os.path.join(d, "feedback-000000.bin" + fl.COMMIT_SUFFIX)
        with open(cpath, "rb") as f:
            raw = f.read()
        first_end = raw.index(b"\n") + 1
        # tear the second commit line mid-record (no trailing newline)
        with open(cpath, "wb") as f:
            f.write(raw[: first_end + (len(raw) - first_end) // 2])
        w = fl.FeedbackWriter(d, page_bytes=1 << 20, rotate_bytes=1 << 20,
                              fsync=True, drop_on_error=False)
        s3 = w.append_seq(_rec_data(3.0), [3.0])
        w.flush()
        w.close()
        got, _ = _read_all(d)
        vio = []
        if int(s1) not in got:
            vio.append(f"first committed seq {s1} lost")
        if int(s2) in got:
            vio.append(f"torn-sidecar page surfaced (seq {s2})")
        if s3 is None or int(s3) not in got:
            vio.append("post-recovery commit hidden by torn sidecar line")
        return vio
    finally:
        shutil.rmtree(d, ignore_errors=True)


def reg_orphan_shard_below_boundary(scratch: str) -> List[str]:
    """A crash between the boundary fsync and the unlinks leaves orphan
    shards below ``compacted_below``; readers must ignore them and a
    cursor at the boundary must not be declared stale."""
    d = tempfile.mkdtemp(prefix="reg-orphan-", dir=scratch)
    try:
        w = fl.FeedbackWriter(d, page_bytes=1 << 20, rotate_bytes=1,
                              fsync=True, drop_on_error=False)
        w.append_seq(_rec_data(1.0), [1.0])
        w.flush()  # shard 0 (rotates)
        s2 = w.append_seq(_rec_data(2.0), [2.0])
        w.flush()  # shard 1
        w.close()
        # boundary says shard 0 is gone, but its files survived
        with open(os.path.join(d, fl.RETENTION_FILE), "w",
                  encoding="utf-8") as f:
            json.dump({"compacted_below": 1}, f)
        got, _ = _read_all(d, {"shard": 1, "off": 0})
        vio = []
        if int(s2) not in got:
            vio.append(f"live seq {s2} unreadable next to orphans")
        if any(v == 1.0 for v in got.values()):
            vio.append("orphan shard below the boundary was served")
        return vio
    finally:
        shutil.rmtree(d, ignore_errors=True)


def reg_manifest_without_model(scratch: str) -> List[str]:
    """An orphan manifest (model unlinked, manifest unlink not yet
    durable) must not confuse discovery or resume."""
    d = tempfile.mkdtemp(prefix="reg-manifest-", dir=scratch)
    try:
        ck.write_checkpoint(ck.publish_path(d, 1), _model_blob(1),
                            round_=1)
        ck.write_checkpoint(ck.publish_path(d, 2), _model_blob(2),
                            round_=2)
        os.unlink(ck.publish_path(d, 2))  # manifest 2 survives
        latest = ck.find_latest_valid(d, silent=True)
        if latest is None or latest[0] != 1:
            return [f"orphan manifest broke resume: {latest}"]
        return []
    finally:
        shutil.rmtree(d, ignore_errors=True)


def reg_tmp_orphan_ignored(scratch: str) -> List[str]:
    """A torn atomic-write temp file must be invisible to checkpoint
    discovery (the ``.*.tmp.*`` naming contract)."""
    d = tempfile.mkdtemp(prefix="reg-tmp-", dir=scratch)
    try:
        ck.write_checkpoint(ck.publish_path(d, 1), _model_blob(1),
                            round_=1)
        with open(os.path.join(d, ".0002.model.tmp.999"), "wb") as f:
            f.write(b"torn half-written checkpoint bytes")
        names = [p for _r, p in ck.list_checkpoints(d)]
        if any(".tmp." in os.path.basename(p) for p in names):
            return ["atomic-write temp file surfaced in discovery"]
        latest = ck.find_latest_valid(d, silent=True)
        if latest is None or latest[0] != 1:
            return [f"torn temp file broke resume: {latest}"]
        return []
    finally:
        shutil.rmtree(d, ignore_errors=True)


def reg_garbage_publish_pointer(scratch: str) -> List[str]:
    """A torn/garbage PUBLISHED.json must read as absent, never raise
    (can only happen if the pointer was written non-atomically)."""
    d = tempfile.mkdtemp(prefix="reg-pointer-", dir=scratch)
    try:
        os.makedirs(d, exist_ok=True)
        with open(ck.pointer_path(d), "wb") as f:
            f.write(b'{"round": 3, "pa')  # torn mid-key
        if ck.read_publish_pointer(d) is not None:
            return ["garbage publish pointer parsed as valid"]
        return []
    finally:
        shutil.rmtree(d, ignore_errors=True)


REGRESSIONS: List[Tuple[str, Callable]] = [
    ("torn-commit-sidecar-append", reg_torn_commit_sidecar_append),
    ("orphan-shard-below-boundary", reg_orphan_shard_below_boundary),
    ("manifest-without-model", reg_manifest_without_model),
    ("tmp-orphan-ignored", reg_tmp_orphan_ignored),
    ("garbage-publish-pointer", reg_garbage_publish_pointer),
]


# ----------------------------------------------------------------------


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tier-1 budget: drop the mid-cut torn states "
                    "(still >= --min-states distinct)")
    ap.add_argument("--stride", type=int, default=0,
                    help="explicit crash-point stride (overrides --smoke)")
    ap.add_argument("--only", choices=[n for n, _w, _c in WORKLOADS],
                    help="run a single workload (debugging)")
    ap.add_argument("--min-states", type=int, default=300,
                    help="fail the verdict below this many distinct "
                    "states (default 300)")
    ap.add_argument("--out", help="write the verdict JSON here")
    args = ap.parse_args(argv)

    faults.reset()
    stride = args.stride or 1
    torn_keeps = 2 if args.smoke else 3
    t0 = time.time()
    scratch = tempfile.mkdtemp(prefix="crash-audit-")
    workloads: Dict[str, dict] = {}
    violations: List[dict] = []
    try:
        for name, workload, checker in WORKLOADS:
            if args.only and name != args.only:
                continue
            res = audit_workload(name, workload, checker, scratch,
                                 stride, torn_keeps)
            violations.extend(res.pop("violations"))
            workloads[name] = res
            print(f"crash_audit: {name}: {res['ops']} ops, "
                  f"{res['explored']} states ({res['distinct']} distinct)",
                  flush=True)
        if not args.only:
            for rname, fn in REGRESSIONS:
                try:
                    msgs = fn(scratch)
                except Exception as e:  # noqa: BLE001
                    msgs = [f"regression raised {type(e).__name__}: {e}"]
                for msg in msgs:
                    violations.append({"workload": f"regression:{rname}",
                                       "k": None, "variant": None,
                                       "keep": None, "msg": msg})
                print(f"crash_audit: regression {rname}: "
                      f"{'FAIL' if msgs else 'ok'}", flush=True)
    finally:
        shutil.rmtree(scratch, ignore_errors=True)

    distinct = sum(w["distinct"] for w in workloads.values())
    explored = sum(w["explored"] for w in workloads.values())
    verdict = "ok"
    if violations:
        verdict = "violations"
    elif not args.only and distinct < args.min_states:
        verdict = f"too few states ({distinct} < {args.min_states})"
    doc = {
        "bench": "crash_audit",
        "workloads": workloads,
        "states_explored": explored,
        "distinct_states": distinct,
        "violations": violations,
        "violations_count": len(violations),
        "wall_s": round(time.time() - t0, 3),
        "verdict": verdict,
    }
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1)
    for v in violations[:50]:
        print(f"crash_audit: VIOLATION [{v['workload']} k={v['k']} "
              f"{v['variant']}/{v['keep']}]: {v['msg']}", flush=True)
    print(f"crash_audit: {explored} states explored, {distinct} distinct, "
          f"{len(violations)} violation(s), "
          f"{doc['wall_s']}s -> {verdict}", flush=True)
    return 0 if verdict == "ok" else 1


if __name__ == "__main__":
    sys.exit(main())
