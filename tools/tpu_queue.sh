#!/bin/bash
# The ONE serialized on-chip measurement queue (round-3 postmortem:
# two concurrent TPU-dialing processes wedged the single-client relay
# for ~8h; everything TPU now goes through this script, under an
# exclusive flock, after a relay-health probe).
#
# Usage: bash tools/tpu_queue.sh [logfile]
# Default log: /tmp/tpu_queue.log (append).
set -u
cd "$(dirname "$0")/.."
LOG=${1:-/tmp/tpu_queue.log}
LOCK=${TPU_RELAY_LOCK:-/tmp/tpu_relay.lock}

exec 9>"$LOCK"
if ! flock -n 9; then
  echo "another TPU run holds $LOCK; refusing to double-dial" >&2
  exit 1
fi
# children (bench.py/bisect tools) must not re-acquire the flock we hold
export TPU_QUEUE_LOCK_HELD=1

PORT=${AXON_RELAY_PORT:-8082}
if ! timeout 3 bash -c "echo > /dev/tcp/127.0.0.1/$PORT" 2>/dev/null; then
  echo "relay dead (port $PORT refused); not dialing" >&2
  exit 2
fi

# QUEUE_HARD_DEADLINE_EPOCH (optional): entries whose budget cannot
# finish before it are skipped, so the queue never holds the relay
# flock into the driver's own end-of-round bench window — a held lock
# there would turn the round's BENCH artifact into a refusal error.
fits_deadline() {
  local budget=$1
  [ -z "${QUEUE_HARD_DEADLINE_EPOCH:-}" ] && return 0
  [ $(($(date +%s) + budget + 120)) -le "$QUEUE_HARD_DEADLINE_EPOCH" ]
}

run() {
  local budget=$1; shift
  if ! fits_deadline "$budget"; then
    echo "=== SKIP (deadline): $* ==="
    return 0
  fi
  echo "=== $* ==="
  # bench.py's own watchdog stays just under this run's budget, so a
  # long-but-healthy sweep is never killed by the 1200s default
  BENCH_WATCHDOG_SEC=$((budget - 120)) \
    timeout "$budget" "$@" 2>&1 | grep -E "bench\[|stage\[|\"metric\"" || true
}

sweep() {
  # sweep <per_variant_budget> python tools/X_bisect.py v1 v2 ...
  # The bisect tools re-arm their watchdog at EACH variant with
  # BENCH_WATCHDOG_SEC, so the external budget must scale with the
  # variant count: per*(n+1) — the +1 covers startup (jax import + TPU
  # dial, which gets its own watchdog arming) — guarantees every
  # per-variant watchdog (per-120) fires before the external `timeout`,
  # never the round-3 rc=124 mode.  Variants MUST be listed explicitly
  # (n=0 would make `timeout 0` disable the backstop entirely).
  local per=$1; shift
  local n=$(($# - 2))   # args after "python <script>"
  if [ "$n" -lt 1 ]; then
    echo "sweep: list variants explicitly (got: $*)" >&2
    return 1
  fi
  if ! fits_deadline $((per * (n + 1))); then
    echo "=== SKIP (deadline): $* ==="
    return 0
  fi
  echo "=== $* (n=$n, per=$per) ==="
  BENCH_WATCHDOG_SEC=$((per - 120)) \
    timeout $((per * (n + 1))) "$@" 2>&1 | grep -E "bench\[|stage\[|\"metric\"" || true
}

{
  date
  # headline FIRST: if the relay window is short, the round's most
  # important artifact (the driver-parseable GoogLeNet number + a warm
  # compile cache for the driver's own run) is secured before anything
  # else spends the window
  run 1800 python bench.py
  # round-3 stranded A/Bs (VERDICT r3 #2), then the round-4 wino
  sweep 900 python tools/googlenet_bisect.py base lrnmm stems2d wino bembed bembed_lrnmm best
  sweep 900 python tools/resnet_bisect.py base stems2d wino
  run 1500 python bench.py --resnet
  run 1500 python bench.py --vgg
  sweep 900 python tools/vgg_bisect.py wino wino2 wino345 wino45
  run 1800 python bench.py --flash
  run 1500 python bench.py --alexnet
  run 1200 python bench.py --pred
  # the one integration never yet exercised on chip: CLI train with the
  # real decode->augment->scan pipeline in-path (log goes to example/)
  if fits_deadline 1800; then
    echo "=== tpu_train_e2e ==="
    timeout 1800 python tools/tpu_train_e2e.py 4096 3 128 2>&1 | tee /tmp/tpu_train_e2e.log | tail -20
  else
    echo "=== SKIP (deadline): tpu_train_e2e ==="
  fi
  # quantized serving A/B (ROADMAP item 3 / PR 10): int8-weight predict
  # programs vs f32 on the serve engine — the on-chip confirmation of
  # the CPU-measured weight-bytes win (doc/performance.md "Quantized
  # inference"); bembed is default-on for these inference builds
  run 900 python tools/serve_bench.py --model googlenet --dev tpu \
    --quant int8 --max-batch 128 --rows 8 --requests 100
  run 600 python tools/serve_bench.py --model mnist_mlp --dev tpu \
    --quant int8 --requests 200
  # serving-fleet burst story (ROADMAP item 1 / PR 12): >= 10^6
  # open-loop requests through the serve data path at a bursty
  # arrival profile — sustained p50/p99 + shed counts are the
  # million-user evidence (doc/serving.md "Serving fleet"); the
  # scaled-down twin runs in the FLEET=1 tier-1 lane
  run 2700 python tools/serve_bench.py --model mnist_mlp --dev tpu \
    --open-loop --burst --base-rate 2000 --burst-rate 8000 --phase 5 \
    --total-requests 1000000 --clients 128 --rows 8 --max-batch 128
  # binary wire data plane (ISSUE 19 / serve/wire.py): the same
  # >= 10^6-request burst story over CXB1 frames + pooled keep-alive
  # clients against a REAL 3-replica fleet front end (doc/serving.md
  # "Binary wire protocol"), plus the JSON-vs-binary closed-loop A/B
  # at serving scale; the scaled-down twin runs in the WIRE=1 tier-1
  # lane and the CPU fleet numbers are committed in bench_history.jsonl
  run 900 python tools/serve_bench.py --model mnist_mlp --dev tpu \
    --wire-ab --rows 32 --concurrency 16 --requests 200 --max-batch 256
  run 2700 python tools/fleet_smoke.py --out /tmp/_wire_burst \
    --no-kill --wire binary --replicas 3 --total-requests 1000000 \
    --base-rate 2000 --burst-rate 8000 --phase 5 --clients 128 \
    --rows 8 --progress-s 30
  # async data-parallel overlap bench (ROADMAP item 5 / PR 13): the
  # on-chip step-wall measurement — per-step fence (sync) vs one
  # round-boundary fence (async_overlap=1, staleness=1) over the same
  # stream (doc/parallel.md "Async data-parallel"); CPU numbers only
  # show dispatch overhead, the chip shows exchange/compute overlap
  run 900 python tools/async_ab.py --overlap-bench --dev tpu \
    --steps 100 --hidden 4096
  # Pallas kernel-library A/B (ISSUE 17 / ops/kernels/): the on-chip
  # half of the measured-verdict promotion — parity gate (compiled
  # Mosaic vs stock lowering) + timed legs per kernel; --record
  # commits the tpu-backend verdicts kernel_lib=auto follows
  # (doc/performance.md "Kernel library").  CPU verdicts are already
  # recorded (conv_block/zero_update reject under interpret emulation,
  # int8_gemm tie-promote); these are the first real MXU numbers
  run 900 python tools/kernel_ab.py --kernel conv_block --record \
    --history /tmp/tpu_kernel_bench.jsonl --json /tmp/kernel_ab_conv_block.json
  run 900 python tools/kernel_ab.py --kernel int8_gemm --record \
    --history /tmp/tpu_kernel_bench.jsonl --json /tmp/kernel_ab_int8_gemm.json
  run 900 python tools/kernel_ab.py --kernel zero_update --record \
    --history /tmp/tpu_kernel_bench.jsonl --json /tmp/kernel_ab_zero_update.json
  # integrity-plane overhead at full size (ISSUE 18 / doc/
  # robustness.md "Integrity plane"): the fingerprint sweep's share of
  # the round wall on-chip at a real model width — the CPU lane (SDC=1
  # tier-1) proves detection/quarantine mechanics at 256 hidden; this
  # is the <=2% bound measured where digest bandwidth actually costs
  run 900 python tools/sdc_smoke.py --overhead-only --dev tpu \
    --hidden 4096 --out /tmp/_sdc_tpu \
    --json /tmp/sdc_overhead_tpu.json
  # data-service A/B at full size (ISSUE 20 / io/dataservice/): the
  # local-vs-shared-fleet amortization measured where decode bandwidth
  # actually costs — full-resolution JPEGs, 2 clients on one warm
  # chunk cache (the CPU lane's 48x48 smoke proves schema + hit-rate
  # mechanics only; these are the real img/s numbers the perf history
  # bands)
  run 900 python tools/io_bench.py 2000 256 --service \
    --json /tmp/dsvc_bench_full.json
  # TPU-backend HLO fusion audit (compile-only; doc/performance.md)
  run 900 python tools/hlo_inspect.py googlenet 128
  run 900 python tools/hlo_inspect.py googlenet 128 conv_branch_embed=1
  run 900 python tools/hlo_inspect.py vgg 128
  date
} 2>&1 | tee -a "$LOG"
