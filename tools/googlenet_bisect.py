"""GoogLeNet step-time bisection (the doc/performance.md discipline,
tools/resnet_bisect.py analog) — isolates where the post-strided-unpool
42 ms/step goes.

Run on the TPU host:

    python tools/googlenet_bisect.py [variant ...]

Variants (default: all):

* base      — the bench conf as-is (lrn=xla)
* lrnmm     — ``lrn_impl = matmul`` on both LRN layers (banded-GEMM
              window sum, ops/lrn.lrn_matmul): the A/B for flipping the
              conf default
* nolrn     — both LRN layers -> relu (~free): the LRN ceiling
* stem1x1   — the 7x7 s2 stem conv -> 1x1 s2 (pad 0; same 112x112x64
              output shape): what conv1 costs
* conv1x1   — EVERY odd-k padded conv -> 1x1 pad 0 (shape-preserving):
              the all-conv ceiling, leaving pools/LRN/fc
* stems2d   — the 7x7 s2 stem conv via the space-to-depth rewrite
              (``conv_s2d = 1``): the stem-conv A/B
* wino      — every 3x3 s1 conv via Winograd F(4x4,3x3)
              (``conv_wino = 1`` global): 4x fewer MACs on the
              inception 3x3 branches
* bembed    — branch-embedding fusion (``conv_branch_embed = 1``):
              each inception (3x3, 5x5) branch pair as ONE
              block-kernel conv — ~3.6x MACs for an adequately-shaped
              GEMM per module
* bembed_lrnmm — bembed + ``lrn_impl = matmul`` (the promotion
              candidate if both win)
"""

import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _sub(conf: str, old: str, new: str) -> str:
    """str.replace that refuses to silently no-op: a drifted builder
    string would otherwise turn an A/B variant into base-vs-base."""
    out = conf.replace(old, new)
    assert out != conf or old == new, f"conf drift: {old!r} not found"
    return out


def _conv_to_1x1(conf: str, only_stem: bool = False) -> str:
    """Rewrite ``kernel_size = k / pad = (k-1)/2`` conv bodies to 1x1
    pad 0 (output shapes preserved; stride untouched)."""
    out = []
    blocks = conf.split("layer[")
    for i, blk in enumerate(blocks):
        if i and re.match(r"[^\]]*\] = conv:", blk):
            is_stem = "conv:conv1\n" in blk
            if (not only_stem) or is_stem:
                blk = re.sub(r"kernel_size = \d+", "kernel_size = 1", blk,
                             count=1)
                blk = re.sub(r"pad = \d+", "pad = 0", blk, count=1)
        out.append(blk)
    return "layer[".join(out)


def variant_conf(name: str, batch: int) -> str:
    from cxxnet_tpu.models import googlenet_conf

    conf = googlenet_conf(batch_size=batch, input_size=224, synthetic=False,
                          dev="tpu")
    if name == "base":
        return conf
    if name == "lrnmm":
        return conf + "lrn_impl = matmul\n"
    if name == "nolrn":
        out = re.sub(
            r"= lrn\n(  local_size[^\n]*\n  alpha[^\n]*\n  beta[^\n]*\n"
            r"  knorm[^\n]*\n)",
            "= relu\n",
            conf,
        )
        assert out != conf, "conf drift: no lrn layers matched"
        return out
    if name == "stem1x1":
        return _conv_to_1x1(conf, only_stem=True)
    if name == "conv1x1":
        return _conv_to_1x1(conf)
    if name == "stems2d":
        # the 7x7 s2 stem via space-to-depth (conv._conv_s2d A/B)
        out = _sub(conf,
            "layer[0->c1] = conv:conv1\n",
            "layer[0->c1] = conv:conv1\n  conv_s2d = 1\n",
        )
        return out
    if name == "wino":
        # global default: conv layers pick it up, 3x3-s1 only (others
        # keep the direct path), non-conv layers ignore the key
        return conf + "conv_wino = 1\n"
    if name == "bembed":
        # branch-embedding fusion: every inception (3x3, 5x5) branch
        # pair as ONE block-kernel conv (net._branch_embed_plan) —
        # ~3.6x MACs for an adequately-shaped GEMM per module
        return conf + "conv_branch_embed = 1\n"
    if name == "bembed_lrnmm":
        # the likely promotion candidate: branch GEMMs + MXU LRN
        return conf + "conv_branch_embed = 1\nlrn_impl = matmul\n"
    if name == "best":
        # every opt-in lever at once (stem s2d + MXU LRN + branch
        # embedding): the upper bound a combined promotion could reach
        out = _sub(conf,
            "layer[0->c1] = conv:conv1\n",
            "layer[0->c1] = conv:conv1\n  conv_s2d = 1\n",
        )
        return out + "conv_branch_embed = 1\nlrn_impl = matmul\n"
    raise SystemExit(f"unknown variant {name}")


if __name__ == "__main__":
    from bisect_common import run_bisect

    run_bisect(variant_conf,
               ["base", "lrnmm", "nolrn", "stem1x1", "conv1x1",
                "stems2d", "wino", "bembed", "bembed_lrnmm", "best"],
               scan_k=50)
