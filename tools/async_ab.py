"""ASYNC=1 lane: bitwise parity + bounded-staleness convergence A/B.

The async data-parallel subsystem (``cxxnet_tpu/parallel/async_ps``,
doc/parallel.md "Async data-parallel") makes two claims with two very
different proof obligations, and this tool runs both:

* ``--parity`` — **bitwise**: a 4-process CPU-mesh CLI train with
  ``async_overlap = 1, staleness = 0`` must write checkpoint CRCs
  IDENTICAL to the synchronous ``det_reduce = 1`` fused step of the
  same conf/seed (same all-gather + ordered fold, same updater math —
  the overlap is dispatch scheduling, not different arithmetic).
  Hard gate: CRC mismatch exits 1.
* default (A/B) — **measured convergence**: ``staleness > 0`` DOES
  change the math (k-step-delayed aggregates), so it is gated the way
  wino/bembed kernel promotions were: REAL handwritten digits (the
  repo's digits.conf recipe, fixed seeds), sync vs staleness in
  {0, 1, 2} on the same stream, final test error + wall-clock deltas
  in a schema-stable verdict JSON.  ``staleness = 0`` must match sync
  EXACTLY; ``staleness = 1`` must stay within ``--tol`` of sync at
  full lr; ``staleness = 2`` at full lr is measured and RECORDED
  (reject expected — delay x momentum instability, the classic
  result) and must pass within ``--tol`` under the standard mitigation
  (lr halved, rounds doubled) against the same-lr sync baseline.  The
  committed CPU verdict lives in example/MNIST/async_ab.json.
* ``--overlap-bench`` — in-process step-wall micro-bench (sync fence
  per step vs one round fence), the TPU-window measurement queued in
  ``tpu_queue.sh`` (CPU numbers are dispatch-overhead weather; the
  chip is where overlap pays).

Usage::

    python tools/async_ab.py --parity --out /tmp/_async      # hard gate
    python tools/async_ab.py --out /tmp/_async               # full A/B
    python tools/async_ab.py --smoke --out /tmp/_async       # CI lane
    python tools/perf_guard.py --bench async_bench \\
        --input /tmp/_async/async_ab.json --history bench_history.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_IMAGES = 256
GLOBAL_BATCH = 32


def _free_port() -> int:
    from cxxnet_tpu.parallel.elastic import free_port

    return free_port()


def make_data(out_dir: str, n_images: int) -> None:
    import numpy as np

    from cxxnet_tpu.io.mnist import write_idx_images, write_idx_labels

    rng = np.random.RandomState(0)
    imgs = rng.randint(0, 256, (n_images, 4, 4)).astype(np.uint8)
    labels = (imgs.reshape(n_images, -1).mean(1) > 127).astype(np.uint8)
    write_idx_images(os.path.join(out_dir, "img.idx"), imgs)
    write_idx_labels(os.path.join(out_dir, "lab.idx"), labels)


def make_conf(out_dir: str, rounds: int, save_model: int) -> str:
    """The MNIST-MLP conf every leg shares (fixed seed; per-leg keys
    ride as CLI overrides).  An eval section scores the full set each
    round so telemetry carries ``test-error`` — the A/B's metric."""
    conf = os.path.join(out_dir, "async_ab.conf")
    with open(conf, "w", encoding="utf-8") as f:
        f.write(f"""
data = train
iter = mnist
  path_img = "{out_dir}/img.idx"
  path_label = "{out_dir}/lab.idx"
  shuffle = 1
  dist_shard = block
iter = end
eval = test
iter = mnist
  path_img = "{out_dir}/img.idx"
  path_label = "{out_dir}/lab.idx"
iter = end
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 16
  init_sigma = 0.1
layer[fc1->out] = fullc:fc2
  nhidden = 2
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,16
batch_size = {GLOBAL_BATCH}
dev = cpu:0-3
num_round = {rounds}
eval_train = 0
eta = 0.1
momentum = 0.9
seed = 7
save_model = {save_model}
metric = error
silent = 1
telemetry = 1
""")
    return conf


def run_leg(conf: str, workdir: str, overrides, nproc: int = 1,
            timeout: float = 240.0, port: int = 0) -> float:
    """One CLI training leg; returns its wall seconds.  ``nproc > 1``
    launches a real jax.distributed job (the parity mode's 4-process
    mesh; gloo collectives, 1 device per process)."""
    ndev = 4 // nproc
    env = {
        **os.environ,
        "PYTHONPATH": REPO,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": f"--xla_force_host_platform_device_count={ndev}",
    }
    procs = []
    t0 = time.time()
    for r in range(nproc):
        d = os.path.join(workdir, f"p{r}")
        os.makedirs(d, exist_ok=True)
        over = list(overrides)
        if nproc > 1:
            over += [f"dist_coordinator=localhost:{port}",
                     f"dist_num_proc={nproc}", f"dist_proc_id={r}",
                     "dev=cpu"]
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "cxxnet_tpu", conf] + over,
            env=env, cwd=d,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        ))
    try:
        # ONE shared deadline for the whole leg, not one per process —
        # a wedged 4-process leg must die at t0+timeout, not at
        # 4 x timeout (which would blow the ASYNC=1 lane's outer
        # budget and lose the diagnostics)
        deadline = t0 + timeout
        outs = [p.communicate(timeout=max(1.0, deadline - time.time()))[0]
                for p in procs]
    except subprocess.TimeoutExpired:
        # kill the leg, then salvage whatever each rank printed — the
        # timeout must surface as a diagnosable RuntimeError the caller
        # seals into the verdict JSON, not a bare stack trace
        for p in procs:
            if p.poll() is None:
                p.kill()
        tails = []
        for r, p in enumerate(procs):
            try:
                o = p.communicate(timeout=5)[0] or b""
            except Exception:  # noqa: BLE001 - salvage is best-effort
                o = b""
            tails.append(f"--- rank {r} (rc={p.returncode}) ---\n"
                         + o.decode(errors="replace")[-2000:])
        raise RuntimeError(
            f"async_ab leg timed out after {timeout:.0f}s "
            f"(overrides={overrides}):\n" + "\n".join(tails)) from None
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, o in zip(procs, outs):
        if p.returncode != 0:
            raise RuntimeError(
                f"async_ab leg failed (rc={p.returncode}, "
                f"overrides={overrides}):\n{o.decode()[-4000:]}")
    return time.time() - t0


def read_telemetry(rank_dir: str) -> dict:
    """Last telemetry record of a leg (final-round eval + async block)."""
    last = {}
    try:
        with open(os.path.join(rank_dir, "telemetry.jsonl"),
                  "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line:
                    last = json.loads(line)
    except (OSError, ValueError):
        return {}
    return last


def read_crcs(rank_dir: str) -> dict:
    from cxxnet_tpu.utils import checkpoint as ckpt

    out = {}
    for round_, path in ckpt.list_checkpoints(
            os.path.join(rank_dir, "models")):
        man = ckpt.read_manifest(path)
        if man is not None:
            out[round_] = man["crc32"]
    return out


def final_error(tele: dict) -> float:
    ev = tele.get("eval") or {}
    for k in sorted(ev):
        if "test-" in k and "error" in k:
            return float(ev[k])
    return float("nan")


# ----------------------------------------------------------------------
def run_parity(out_dir: str, rounds: int, timeout: float) -> dict:
    """The hard gate: 4-process async(staleness=0) CRCs == 4-process
    det_reduce sync CRCs, checkpoint for checkpoint."""
    workdir = os.path.join(out_dir, "parity")
    conf = make_conf(out_dir, rounds, save_model=1)
    legs = {}
    for name, over in (
            ("sync", ["det_reduce=1"]),
            ("async0", ["async_overlap=1", "staleness=0"])):
        wall = run_leg(conf, os.path.join(workdir, name), over,
                       nproc=4, timeout=timeout, port=_free_port())
        crcs = read_crcs(os.path.join(workdir, name, "p0"))
        legs[name] = {"wall_sec": round(wall, 3), "crcs": crcs}
    problems = []
    if not legs["sync"]["crcs"]:
        problems.append("parity: sync leg wrote no checkpoints")
    if legs["sync"]["crcs"] != legs["async0"]["crcs"]:
        problems.append(
            f"BITWISE PARITY FAILED: sync CRCs {legs['sync']['crcs']} "
            f"!= async CRCs {legs['async0']['crcs']}")
    return {
        "crc_equal": legs["sync"]["crcs"] == legs["async0"]["crcs"]
        and bool(legs["sync"]["crcs"]),
        "rounds": rounds,
        "sync_wall_sec": legs["sync"]["wall_sec"],
        "async_wall_sec": legs["async0"]["wall_sec"],
        "crcs": {str(k): f"{v:#010x}" for k, v in
                 sorted(legs["sync"]["crcs"].items())},
        "problems": problems,
    }


def make_digits_conf(out_dir: str) -> str:
    """The REAL-data A/B conf: the repo's digits.conf recipe (UCI
    handwritten digits via sklearn, idx-encoded by
    tools/make_digits_idx.py) on the 4-device mesh — batch 48 so the
    data axis divides.  eta / num_round / async keys ride per leg as
    CLI overrides (last entry wins)."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from make_digits_idx import write_digits_idx

    data_dir = os.path.join(out_dir, "data")
    write_digits_idx(data_dir)
    conf = os.path.join(out_dir, "async_digits.conf")
    with open(conf, "w", encoding="utf-8") as f:
        f.write(f"""
data = train
iter = mnist
  path_img = "{data_dir}/digits-train-images-idx3-ubyte"
  path_label = "{data_dir}/digits-train-labels-idx1-ubyte"
  shuffle = 1
iter = end
eval = test
iter = mnist
  path_img = "{data_dir}/digits-t10k-images-idx3-ubyte"
  path_label = "{data_dir}/digits-t10k-labels-idx1-ubyte"
iter = end
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 100
  init_sigma = 0.01
layer[+1:sg1] = sigmoid:se1
layer[sg1->fc2] = fullc:fc2
  nhidden = 10
  init_sigma = 0.01
layer[+0] = softmax
netconfig=end
input_shape = 1,1,64
batch_size = 48
dev = cpu:0-3
eval_train = 0
random_type = gaussian
seed = 1
eta = 0.1
momentum = 0.9
save_model = 0
metric[label] = error
silent = 1
telemetry = 1
""")
    return conf


def run_ab(out_dir: str, rounds: int, tol: float, timeout: float,
           smoke: bool = False) -> dict:
    """The convergence A/B on real digits: single process over the
    4-device mesh, save_model=0 (no checkpoint drain — the staleness
    pipeline persists across rounds; the resync period caps it).

    Per-leg verdicts: ``exact`` (bitwise-math legs), ``pass`` /
    ``reject`` by ``tol`` for the stale legs — a reject is a RECORDED
    measurement (the wino-verdict discipline), and only gates the lane
    where the contract says it must pass."""
    workdir = os.path.join(out_dir, "ab")
    conf = make_digits_conf(out_dir)
    asynck = ["async_overlap=1", "async_resync_period=1000"]
    specs = [
        # name, overrides, baseline leg, must_pass
        ("sync", ["det_reduce=1", f"num_round={rounds}"], None, True),
        ("staleness0", asynck + ["staleness=0", f"num_round={rounds}"],
         "sync", True),
        ("staleness1", asynck + ["staleness=1", f"num_round={rounds}"],
         "sync", True),
        ("staleness2", asynck + ["staleness=2", f"num_round={rounds}"],
         "sync", False),  # full-lr delay-2: measured, reject expected
        ("sync_lr_backoff",
         ["det_reduce=1", "eta=0.05", f"num_round={2 * rounds}"],
         None, True),
        ("staleness2_lr_backoff",
         asynck + ["staleness=2", "eta=0.05", f"num_round={2 * rounds}"],
         "sync_lr_backoff", True),  # the standard mitigation must work
    ]
    if smoke:  # the CI lane: exactness + schema only, tiny budget
        specs = [s for s in specs if s[0] in ("sync", "staleness0")]
    legs, problems = {}, []
    for name, over, _base, _must in specs:
        d = os.path.join(workdir, name)
        wall = run_leg(conf, d, over, nproc=1, timeout=timeout)
        tele = read_telemetry(os.path.join(d, "p0"))
        err = final_error(tele)
        leg = {"final_err": err, "wall_sec": round(wall, 3),
               "rounds": tele.get("round")}
        a = tele.get("async")
        if a:
            leg["overlap_fraction"] = a.get("overlap_fraction")
            leg["pushes"] = a.get("pushes")
            leg["applies"] = a.get("applies")
        legs[name] = leg
        if err != err:  # NaN
            problems.append(f"{name}: no test-error in telemetry")
    deltas = {}
    for name, _over, base, must_pass in specs:
        if base is None:
            legs[name]["verdict"] = "baseline"
            continue
        base_err = legs[base]["final_err"]
        delta = abs(legs[name]["final_err"] - base_err)
        if name == "staleness0":
            ok = legs[name]["final_err"] == base_err
            legs[name]["verdict"] = "exact" if ok else "reject"
            if not ok:
                problems.append(
                    f"staleness=0 final error {legs[name]['final_err']} "
                    f"!= sync {base_err} (must be EXACT — same math)")
            continue
        deltas[name] = {
            "err_delta": round(delta, 6),
            "vs": base,
            "wall_delta_sec": round(
                legs[name]["wall_sec"] - legs[base]["wall_sec"], 3),
        }
        ok = delta <= tol
        legs[name]["verdict"] = "pass" if ok else "reject"
        if must_pass and not ok:
            problems.append(
                f"{name}: final error {legs[name]['final_err']} drifted "
                f"{delta:.4f} > tol {tol} from {base} {base_err}")
    return {"legs": legs, "deltas": deltas, "tol": tol,
            "dataset": "uci-digits (tools/make_digits_idx.py)",
            "problems": problems}


def run_overlap_bench(dev: str, steps: int, hidden: int) -> dict:
    """In-process step-wall micro-bench on ``dev``: per-step fence
    (sync) vs one round-boundary fence (async) over the same stream.
    Queued for the TPU window in tpu_queue.sh — CPU numbers only show
    dispatch overhead, the chip shows exchange/compute overlap."""
    import numpy as np

    from cxxnet_tpu.io.data import DataBatch
    from cxxnet_tpu.nnet.trainer import NetTrainer

    bs, nin, nout = 64, 64, 8
    cfg = [
        ("dev", dev), ("batch_size", str(bs)),
        ("input_shape", f"1,1,{nin}"), ("seed", "7"), ("eta", "0.05"),
        ("eval_train", "0"),
        ("netconfig", "start"),
        ("layer[0->1]", "fullc:fc1"), ("nhidden", str(hidden)),
        ("layer[1->2]", "sigmoid"),
        ("layer[2->3]", "fullc:fc2"), ("nhidden", str(nout)),
        ("layer[3->3]", "softmax"),
        ("netconfig", "end"),
    ]

    def build(extra):
        tr = NetTrainer()
        tr.set_params(cfg + extra)
        tr.init_model()
        return tr

    rng = np.random.RandomState(3)
    batches = [
        DataBatch(data=rng.randn(bs, nin).astype(np.float32),
                  label=rng.randint(0, nout, (bs, 1)).astype(np.float32))
        for _ in range(steps)
    ]
    out = {"dev": dev, "steps": steps, "hidden": hidden}
    for name, extra in (("sync", [("det_reduce", "1")]),
                        ("async", [("async_overlap", "1"),
                                   ("staleness", "1"),
                                   ("async_resync_period", "1")])):
        tr = build(extra)
        if name == "async" and not tr._async_active():
            raise SystemExit(
                f"overlap-bench: async mode inactive on dev={dev!r} "
                "(1-device mesh?) — the measurement would time a no-op")
        tr.update(batches[0])  # warm the compiles outside the timing
        tr.sync() if name == "sync" else tr.async_round_end(0)
        t0 = time.perf_counter()
        for b in batches:
            tr.update(b)
            if name == "sync":
                tr.sync()
        if name == "async":
            tr.async_round_end(1)
        wall = time.perf_counter() - t0
        out[f"{name}_step_wall_sec"] = round(wall / steps, 6)
        if name == "async":
            out["overlap_fraction"] = round(
                tr.async_snapshot()["overlap_fraction"], 4)
    out["speedup"] = round(
        out["sync_step_wall_sec"] / out["async_step_wall_sec"], 3)
    return out


def validate_doc(doc: dict):
    problems = []
    for key in ("bench", "ts", "verdict"):
        if key not in doc:
            problems.append(f"verdict missing key {key!r}")
    if doc.get("verdict") not in ("ok", "fail"):
        problems.append(f"bad verdict {doc.get('verdict')!r}")
    legs = (doc.get("ab") or {}).get("legs")
    if legs is not None:
        for name, leg in legs.items():
            for f in ("final_err", "wall_sec"):
                if not isinstance(leg.get(f), (int, float)):
                    problems.append(f"leg {name}: missing {f}")
    return problems


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="/tmp/_async_ab")
    ap.add_argument("--rounds", type=int, default=15,
                    help="A/B rounds at full lr (the digits.conf "
                         "budget; lr-backoff legs run 2x)")
    ap.add_argument("--tol", type=float, default=0.1,
                    help="allowed |final_err - sync| for staleness>0")
    ap.add_argument("--timeout", type=float, default=240.0,
                    help="per-leg wall budget (seconds)")
    ap.add_argument("--parity", action="store_true",
                    help="ONLY the 4-process bitwise parity gate")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny A/B + parity (the ASYNC=1 CI lane)")
    ap.add_argument("--overlap-bench", action="store_true",
                    help="in-process step-wall micro-bench (TPU queue)")
    ap.add_argument("--dev", default="cpu:0-3",
                    help="--overlap-bench device string")
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--json", dest="json_path", default="")
    args = ap.parse_args()

    os.makedirs(args.out, exist_ok=True)
    doc = {"bench": "async_ab", "ts": time.time()}
    problems = []

    if args.overlap_bench:
        if ":" not in args.dev:
            # a bare platform ("tpu") would parse to ONE device and
            # silently deactivate async mode (1-device no-op) — expand
            # to every device of the platform so the bench measures a
            # real exchange; cpu needs the forced-host-count flag below
            # and therefore must be passed explicitly (e.g. cpu:0-3)
            if args.dev.startswith("cpu"):
                ap.error("--overlap-bench needs an explicit multi-"
                         "device cpu spec (e.g. --dev cpu:0-3)")
            import jax

            n = jax.device_count()
            if n < 2:
                ap.error(f"--overlap-bench: only {n} {args.dev} "
                         "device(s) visible; async mode needs >= 2")
            args.dev = f"{args.dev}:0-{n - 1}"
        if args.dev.startswith("cpu") and ":" in args.dev:
            # the in-process bench runs on a forced multi-device host
            # platform (the subprocess legs set this per leg); must
            # land before jax initializes its backends
            spec = args.dev.split(":", 1)[1]
            n = 1 + max(int(p.split("-")[-1]) for p in spec.split(","))
            flags = os.environ.get("XLA_FLAGS", "")
            if "host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    f"{flags} --xla_force_host_platform_device_count={n}"
                ).strip()
        doc["overlap"] = run_overlap_bench(args.dev, args.steps,
                                           args.hidden)
        o = doc["overlap"]
        # relay-greppable one-liner (the tpu_queue.sh filter keeps
        # only bench[/stage[ lines from a TPU-window run)
        print(f"bench[async_overlap:{o['dev']}] "
              f"sync_step={o['sync_step_wall_sec']}s "
              f"async_step={o['async_step_wall_sec']}s "
              f"speedup={o['speedup']}x "
              f"overlap_fraction={o['overlap_fraction']}")
    else:
        try:
            make_data(args.out, 64 if args.smoke else N_IMAGES)
            # the parity gate always runs in data mode: a committed A/B
            # verdict without the bitwise proof is only half the
            # contract
            doc["parity"] = run_parity(args.out, 2 if args.smoke else 3,
                                       args.timeout)
            problems += doc["parity"]["problems"]
            if not args.parity:
                doc["ab"] = run_ab(args.out,
                                   3 if args.smoke else args.rounds,
                                   args.tol, args.timeout,
                                   smoke=args.smoke)
                problems += doc["ab"]["problems"]
        except RuntimeError as e:
            # a failed/timed-out leg still produces a fail-verdict
            # artifact with the captured child output — perf_guard and
            # the lane diagnose from the JSON, never from a stack trace
            problems.append(f"leg failure: {str(e)[:6000]}")

    doc["problems"] = problems
    doc["verdict"] = "ok" if not problems else "fail"
    schema_problems = validate_doc(doc)
    if schema_problems:
        # seal the schema failures INTO the written artifact — the
        # committed JSON must never say "ok" while the exit code says
        # fail (perf_guard and the example verdict consume the file)
        problems += schema_problems
        doc["problems"] = problems
        doc["verdict"] = "fail"
    json_path = args.json_path or os.path.join(args.out, "async_ab.json")
    with open(json_path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1)
    print(json.dumps(doc, indent=1))
    for p in problems:
        print(f"FAIL {p}", file=sys.stderr)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
