#!/usr/bin/env python
"""im2bin: pack images listed in a .lst file into CXBP binary pages.

Parity with the reference packer (``/root/reference/tools/im2bin.cpp``):

    python tools/im2bin.py image.lst image_root output.bin

``image.lst`` lines are ``index \t label(s) \t filename`` (tab-separated);
``image_root`` is prefixed to each filename.  Blobs are stored as-is
(JPEG bytes) in ~64MB pages; the reader decodes them off-thread
(native/cxxnet_io.cc).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from cxxnet_tpu.io.imgbin import BinPageWriter, parse_lst_line  # noqa: E402


def main(argv) -> int:
    if len(argv) < 4:
        print(__doc__)
        return 1
    lst_path, root, out_path = argv[1], argv[2], argv[3]
    writer = BinPageWriter(out_path)
    n = 0
    with open(lst_path, "r", encoding="utf-8") as f:
        for line in f:
            if not line.strip():
                continue
            _, _, fname = parse_lst_line(line)
            with open(os.path.join(root, fname), "rb") as img:
                writer.push(img.read())
            n += 1
            if n % 1000 == 0:
                print(f"packed {n} images", file=sys.stderr)
    writer.close()
    print(f"wrote {n} images to {out_path}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
