#!/usr/bin/env python
"""im2bin: pack images listed in a .lst file into binary pages.

Parity with the reference packer (``/root/reference/tools/im2bin.cpp``):

    python tools/im2bin.py image.lst image_root output.bin [--format ref]

``image.lst`` lines are ``index \t label(s) \t filename`` (tab-separated);
``image_root`` is prefixed to each filename.  Blobs are stored as-is
(JPEG bytes); the reader decodes them off-thread (native/cxxnet_io.cc).

``--format cxbp`` (default) writes this framework's CXBP pages;
``--format ref`` writes the reference's BinaryPage bit-format
(io.h:225-300), byte-compatible with cxxnet's own tools.  The reader
auto-detects either, so the flag only matters for interop with the
reference binary.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from cxxnet_tpu.io.imgbin import (  # noqa: E402
    BinPageWriter,
    RefBinPageWriter,
    parse_lst_line,
)


def main(argv) -> int:
    fmt = "cxbp"
    if "--format" in argv:
        i = argv.index("--format")
        fmt = argv[i + 1] if i + 1 < len(argv) else ""
        argv = argv[:i] + argv[i + 2:]
    else:
        for i, a in enumerate(argv):
            if a.startswith("--format="):
                fmt = a.split("=", 1)[1]
                argv = argv[:i] + argv[i + 1:]
                break
    extra = [a for a in argv[1:] if a.startswith("--")]
    if len(argv) < 4 or fmt not in ("cxbp", "ref") or extra:
        if extra:
            print(f"unknown option(s): {' '.join(extra)}", file=sys.stderr)
        print(__doc__)
        return 1
    lst_path, root, out_path = argv[1], argv[2], argv[3]
    writer = (RefBinPageWriter if fmt == "ref" else BinPageWriter)(out_path)
    n = 0
    with open(lst_path, "r", encoding="utf-8") as f:
        for line in f:
            if not line.strip():
                continue
            _, _, fname = parse_lst_line(line)
            with open(os.path.join(root, fname), "rb") as img:
                writer.push(img.read())
            n += 1
            if n % 1000 == 0:
                print(f"packed {n} images", file=sys.stderr)
    writer.close()
    print(f"wrote {n} images to {out_path}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
