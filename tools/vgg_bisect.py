"""VGG-16 step-time bisection (tools/resnet_bisect.py discipline) —
the Winograd rollout A/B at per-stage granularity.

The F(4x4,3x3) rewrite inflates each conv's input 2.25x in HBM
(doc/performance.md, Winograd section), so the big early layers
(224/112px) may trade worse than the late ones; these variants bound
the sweet spot before promoting a conf default.

Run on the TPU host (through tools/tpu_queue.sh):

    python tools/vgg_bisect.py [variant ...]

Variants (default: all):

* base       — vgg16_conf as-is (direct convs)
* wino       — conv_wino = 1 globally (all 3x3 s1 convs; conv1_1 is
               Cin=3 and keeps the direct path via the Cin>=8 gate)
* wino2      — conv_wino = 2 globally: the F(2x2,3x3) tile (2.25x MAC
               reduction, near-direct bf16 numerics)
* wino45     — Winograd only on stages 4-5 (28/14px, C=512): smallest
               HBM inflation, biggest per-FLOP MXU benefit
* wino345    — Winograd on stages 3-5 (56px and down)
"""

import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _wino_on_layers(conf: str, want) -> str:
    """Insert ``conv_wino = 1`` into the body of the conv layers whose
    name matches ``want`` (a predicate over the layer tag)."""
    out = []
    hits = 0
    for i, blk in enumerate(conf.split("layer[")):
        m = re.match(r"[^\]]*\] = conv:([\w.]+)\n", blk) if i else None
        if m and want(m.group(1)):
            head, rest = blk.split("\n", 1)
            blk = head + "\n  conv_wino = 1\n" + rest
            hits += 1
        out.append(blk)
    assert hits, "no conv layers matched the variant predicate"
    return "layer[".join(out)


def variant_conf(name: str, batch: int) -> str:
    from cxxnet_tpu.models import vgg16_conf

    conf = vgg16_conf(batch_size=batch, input_size=224, synthetic=False,
                      dev="tpu")
    if name == "base":
        return conf
    if name == "wino":
        return conf + "conv_wino = 1\n"
    if name == "wino2":
        return conf + "conv_wino = 2\n"
    if name == "wino45":
        return _wino_on_layers(
            conf, lambda tag: re.match(r"conv[45]_", tag) is not None
        )
    if name == "wino345":
        return _wino_on_layers(
            conf, lambda tag: re.match(r"conv[345]_", tag) is not None
        )
    raise SystemExit(f"unknown variant {name}")


if __name__ == "__main__":
    from bisect_common import run_bisect

    run_bisect(variant_conf,
               ["base", "wino", "wino2", "wino45", "wino345"],
               scan_k=20)
