#!/usr/bin/env python
"""Multi-tenant continuous-learning smoke: a real ``task=loop_fleet``
process, end to end (ISSUE 14 acceptance).

Launches ``python -m cxxnet_tpu <conf> task=loop_fleet`` hosting TWO
tenants (alpha, beta) on one device pool — each with its own model_dir,
feedback log, fine-tune loop, per-slice publish gate and retention
sweeper — behind one HTTP front door with per-model routing, and
verifies every claim from the outside:

* **per-slice rejection** — alpha is fed feedback whose class-2 rows
  are deliberately relabeled; the slice gate must reject the candidate
  NAMING the sacrificed cohort in the ``loop.reject`` event, with the
  cycle's lineage attributing it to the exact feedback seq range;
* **both tenants publish** — correct feedback then drives BOTH loops
  through their per-slice gates to a publish + engine hot reload
  (``/healthz`` per-model rounds advance), while the colocated serve
  plane's p99 alert (``alert=``) never fires and no tune cycle sheds;
* **retention** — compaction deletes >= 1 consumed shard per the
  ``loop.compact`` events and ``feedback_disk_bytes{tenant}`` DROPS
  from its ingest peak;
* **crash safety** — the fleet process is SIGKILLed (kill -9), the
  kill-window mid-compaction state (retention pointer advanced, unlinks
  not yet run) is imposed on alpha's log, and every remaining record
  must still read back CRC-verified, with the next sweep deleting the
  orphans and never moving the boundary.

Emits one JSON verdict line on stdout; wired into tier-1 as the opt-in
``TENANT=1`` lane (tools/run_tier1.sh) with a ``tenant_bench``
flattener in tools/perf_guard.py.

Usage: python tools/tenant_smoke.py [--out DIR] [--records N]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import signal
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

CONF = """
data = train
iter = synthetic
  nsample = 256
  input_shape = 1,1,16
  nclass = 4
  seed_data = 1
iter = end
eval = heldout
iter = synthetic
  nsample = 256
  input_shape = 1,1,16
  nclass = 4
  seed_data = 1
iter = end

netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 16
  init_sigma = 0.1
layer[+1:a1] = relu:a1
layer[a1->out] = fullc:fc2
  nhidden = 4
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end

input_shape = 1,1,16
batch_size = 32
dev = cpu
eta = 0.05
metric = error

loop_min_records = 200
loop_rounds_per_cycle = 2
loop_replay_ratio = 0.25
publish_slice_floor = 0.08
publish_slice_min_count = 4
feedback_page_bytes = 4096
feedback_rotate_bytes = 8192
feedback_retain_shards = 0

tenant = alpha
  model_dir = {alpha_mdir}
  feedback_dir = {alpha_fdir}
tenant = end
tenant = beta
  model_dir = {beta_mdir}
  feedback_dir = {beta_fdir}
tenant = end
"""


def _post(port: int, path: str, obj: dict, timeout: float = 30.0) -> dict:
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(obj).encode("utf-8"),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _get(port: int, path: str, timeout: float = 10.0):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=timeout
    ) as r:
        body = r.read()
    return body.decode() if path == "/metricsz" else json.loads(body)


def _events(path: str, kind: str, tenant: str | None = None):
    out = []
    try:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                try:
                    e = json.loads(line)
                except ValueError:
                    continue
                if e.get("kind") != kind:
                    continue
                if tenant is not None and e.get("tenant") != tenant:
                    continue
                out.append(e)
    except OSError:
        pass
    return out


def _gauge(mez: str, family: str, **labels) -> float | None:
    """One labeled gauge value out of exposition text."""
    for line in mez.splitlines():
        if not line.startswith(family):
            continue
        if all(f'{k}="{v}"' in line for k, v in labels.items()):
            try:
                return float(line.rsplit(None, 1)[1])
            except ValueError:
                return None
    return None


def _wait_for(predicate, what: str, timeout_s: float = 180.0,
              poll_s: float = 0.5):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        got = predicate()
        if got:
            return got
        time.sleep(poll_s)
    raise TimeoutError(f"timed out after {timeout_s:.0f}s waiting for {what}")


def _fail(msg: str, proc=None) -> None:
    if proc is not None:
        proc.kill()
        out = proc.stdout.read() if proc.stdout else ""
        sys.stderr.write(f"--- loop_fleet output ---\n{out}\n")
    print(json.dumps({"ok": False, "error": msg}), flush=True)
    raise SystemExit(1)


def _train_checkpoint(mdir: str, seed: int):
    """One quick training epoch -> round-1 serving checkpoint; returns
    the full (data, labels) arrays for the feedback phases."""
    import numpy as np

    from cxxnet_tpu import config as cfgmod
    from cxxnet_tpu.io.data import create_iterator
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.utils import checkpoint as ckpt

    cfg = cfgmod.parse_pairs(CONF.format(
        alpha_mdir="x", alpha_fdir="x", beta_mdir="x", beta_fdir="x"))
    shared, _tenants = cfgmod.split_tenant_sections(cfg)
    split = cfgmod.split_sections(shared)
    tr = NetTrainer()
    tr.set_params(split.global_entries)
    tr.set_param("seed", str(seed))
    tr.init_model()
    it = create_iterator(split.sections[0].entries)
    it.set_param("batch_size", "32")
    it.init()
    rows, labs = [], []
    while it.next():
        b = it.value()
        rows.append(np.asarray(b.data).copy())
        labs.append(np.asarray(b.label).copy())
        tr.update_all(b.data, b.label)
    os.makedirs(mdir, exist_ok=True)
    ckpt.write_checkpoint(
        ckpt.publish_path(mdir, 1), tr.checkpoint_bytes(), round_=1,
        net_fp=tr.net_fp(),
    )
    return np.concatenate(rows), np.concatenate(labs)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None,
                    help="workdir (default: a fresh temp dir)")
    ap.add_argument("--records", type=int, default=400,
                    help="correct-phase feedback records per tenant")
    args = ap.parse_args()
    t_start = time.monotonic()
    work = args.out or tempfile.mkdtemp(prefix="tenant_smoke_")
    os.makedirs(work, exist_ok=True)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import numpy as np

    dirs = {f"{t}_{k}": os.path.join(work, t, k)
            for t in ("alpha", "beta") for k in ("models", "feedback")}
    conf_path = os.path.join(work, "fleet.conf")
    with open(conf_path, "w", encoding="utf-8") as f:
        f.write(CONF.format(
            alpha_mdir=dirs["alpha_models"],
            alpha_fdir=dirs["alpha_feedback"],
            beta_mdir=dirs["beta_models"],
            beta_fdir=dirs["beta_feedback"]))
    events_path = os.path.join(work, "events.jsonl")

    X, Y = _train_checkpoint(dirs["alpha_models"], seed=0)
    _train_checkpoint(dirs["beta_models"], seed=1)

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, "-m", "cxxnet_tpu", conf_path,
         "task=loop_fleet", "serve_port=0", "loop_cycle_period_s=0.5",
         # the colocated serve plane's SLO bound: a mean request
         # latency alert that must stay silent under this light load
         "alert=serve_p99:serve_request_latency_seconds_mean:>:5",
         "alert_period_s=0.5",
         f"event_log={events_path}", "silent=0"],
        env=env, cwd=work, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )
    port = None
    try:
        t0 = time.monotonic()
        for line in proc.stdout:
            sys.stderr.write(line)
            m = re.search(r"http://[^:]+:(\d+)", line)
            if m:
                port = int(m.group(1))
                break
            if time.monotonic() - t0 > 180 or proc.poll() is not None:
                break
        if port is None:
            _fail("loop_fleet never reported a ready port", proc)
        import threading

        threading.Thread(
            target=lambda: [None for _ in proc.stdout], daemon=True
        ).start()

        h0 = _get(port, "/healthz")
        models = h0.get("models") or {}
        if set(models) != {"alpha", "beta"}:
            _fail(f"/healthz models block wrong: {sorted(models)}", proc)
        rounds0 = {t: models[t]["round"] for t in models}

        # unknown model: 404 with the machine-readable reason token
        try:
            _post(port, "/predict", {"data": X[:2].tolist(),
                                     "model": "ghost"})
            _fail("unknown model did not 404", proc)
        except urllib.error.HTTPError as e:
            body = json.loads(e.read())
            if e.code != 404 or body.get("reason") != "unknown_model":
                _fail(f"unknown-model reply wrong: {e.code} {body}", proc)

        def post_rows(model, data, labels, chunk=32):
            n = 0
            for lo in range(0, data.shape[0], chunk):
                out = _post(port, "/feedback", {
                    "model": model,
                    "data": data[lo: lo + chunk].tolist(),
                    "label": labels[lo: lo + chunk].tolist(),
                })
                n += out["appended"]
            return n

        disk_peak: dict = {}

        def fold_fs_peak():
            # the gauge is set at sweep time (post-compaction), so the
            # pre-compaction peak must be sampled from the filesystem
            # while the freshly-ingested shards still exist
            for t in ("alpha", "beta"):
                d = dirs[f"{t}_feedback"]
                try:
                    total = sum(
                        os.path.getsize(os.path.join(d, f))
                        for f in os.listdir(d)
                        if f.startswith("feedback-"))
                except OSError:
                    continue
                disk_peak[t] = max(disk_peak.get(t, 0.0), float(total))

        def track_disk():
            mez = _get(port, "/metricsz")
            for t in ("alpha", "beta"):
                v = _gauge(mez, "feedback_disk_bytes", tenant=t)
                if v is not None:
                    disk_peak[t] = max(disk_peak.get(t, 0.0), v)
            return mez

        # ---- phase A: cohort-poisoned feedback -> per-slice reject.
        # Every class-2 row is relabeled 3: the fine-tuned candidate
        # sacrifices cohort class:2, which the slice gate must reject
        # BY NAME even though other cohorts hold or improve.
        ingested = 0
        sel = np.where(Y.reshape(-1) == 2)[0]
        idx = sel[np.arange(300) % sel.shape[0]]
        ingested += post_rows("alpha", X[idx],
                              np.full(idx.shape[0], 3.0))
        fold_fs_peak()
        slice_rejects = _wait_for(
            lambda: (track_disk() and False) or [
                e for e in _events(events_path, "loop.reject",
                                   tenant="alpha")
                if e.get("cohort")],
            "the per-slice gate to reject alpha's cohort-poisoned "
            "candidate")
        # the slice gate names the WORST-regressed cohort; under the
        # class-2 relabeling that is usually class:2 itself but boundary
        # shifts can sink a neighboring class further — any named
        # cohort is the contract
        rej = slice_rejects[0]
        if not re.fullmatch(r"(class|source):.+", str(rej["cohort"])):
            _fail(f"reject named no cohort: {rej}", proc)
        lin = rej.get("lineage") or {}
        if not (isinstance(lin.get("first_seq"), int)
                and isinstance(lin.get("last_seq"), int)
                and lin["last_seq"] >= lin["first_seq"]):
            _fail(f"slice reject not lineage-attributable: {lin}", proc)
        _wait_for(lambda: _events(events_path, "loop.rollback",
                                  tenant="alpha"),
                  "alpha's trainer rollback")

        # ---- phase B: correct feedback -> BOTH tenants publish
        # through their per-slice gates
        idx = np.arange(args.records) % X.shape[0]
        ingested += post_rows("alpha", X[idx], Y[idx])
        ingested += post_rows("beta", X[idx], Y[idx])
        fold_fs_peak()
        publishes = {}
        for tname in ("alpha", "beta"):
            publishes[tname] = _wait_for(
                lambda t=tname: (track_disk() and False) or _events(
                    events_path, "loop.publish", tenant=t),
                f"{tname}'s publish through the per-slice gate")
        h1 = _get(port, "/healthz")
        rounds1 = {t: h1["models"][t]["round"] for t in h1["models"]}
        for t in ("alpha", "beta"):
            if rounds1[t] <= rounds0[t]:
                _fail(f"{t} never hot-reloaded a published round", proc)

        # ---- retention: compaction observed, disk bytes dropped
        compacts = _wait_for(
            lambda: (track_disk() and False) or [
                e for e in _events(events_path, "loop.compact")
                if e.get("deleted_shards", 0) >= 1],
            "a compaction that deleted >= 1 consumed shard")
        mez = track_disk()
        disk_final = {t: _gauge(mez, "feedback_disk_bytes", tenant=t)
                      for t in ("alpha", "beta")}
        compacted_tenants = {e.get("tenant") for e in compacts}
        dropped = [t for t in compacted_tenants
                   if disk_final.get(t) is not None
                   and disk_final[t] < disk_peak.get(t, 0.0)]
        if not dropped:
            _fail(f"feedback_disk_bytes never dropped: peak={disk_peak} "
                  f"final={disk_final}", proc)

        # ---- the SLO overlay never engaged: no alert fired, no shed
        alertz = _get(port, "/alertz")
        firing = alertz.get("firing", [])
        sheds = _events(events_path, "tenant.shed")
        if firing or sheds:
            _fail(f"serve SLO engaged under light load: firing={firing} "
                  f"sheds={len(sheds)}", proc)

        # ---- kill -9, then prove the log survives a crash landing in
        # compaction's danger window (pointer durable, unlinks not run)
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
        from cxxnet_tpu.loop.feedback_log import (
            RETENTION_FILE, FeedbackReader, list_shards, read_retention)
        from cxxnet_tpu.loop.retention import RetentionOptions, Sweeper
        from cxxnet_tpu.obs import registry as obs_registry

        fdir = dirs["alpha_feedback"]
        with open(os.path.join(fdir, "cursor.json")) as f:
            cursor = json.load(f)
        boundary = max(read_retention(fdir)["compacted_below"],
                       cursor["shard"])
        with open(os.path.join(fdir, RETENTION_FILE), "w") as f:
            json.dump({"compacted_below": boundary}, f)
        reader = FeedbackReader(fdir)

        def bad_pages():
            fam = obs_registry().snapshot().get(
                "loop_feedback_bad_pages_total", {})
            return sum(fam.values()) if fam else 0

        bad0 = bad_pages()
        recs, _ = reader.read_since(cursor)  # CRC-verifying read
        crc_ok = bad_pages() == bad0
        swept = Sweeper(fdir, RetentionOptions(0, 0)).sweep(cursor)
        orphans_left = [i for i, _ in list_shards(fdir) if i < boundary]
        crash_ok = (crc_ok and not orphans_left
                    and swept["compacted_below"] == boundary)

        verdict = {
            "ok": True,
            "tenants": 2,
            "records": ingested,
            "slice_reject": {"cohort": rej["cohort"],
                             "lineage": lin,
                             "reason": rej["reason"]},
            "published": {t: len(v) for t, v in publishes.items()},
            "rounds_before": rounds0,
            "rounds_after": rounds1,
            "compactions": len(compacts),
            "compacted_shards": sum(e.get("deleted_shards", 0)
                                    for e in compacts),
            "compacted_bytes": sum(e.get("deleted_bytes", 0)
                                   for e in compacts),
            "disk_bytes_peak": disk_peak,
            "disk_bytes_final": disk_final,
            "alerts_fired": len(firing),
            "sheds": len(sheds),
            "crc_ok_after_kill": bool(crash_ok),
            "records_after_kill": len(recs),
            "elapsed_s": round(time.monotonic() - t_start, 1),
        }
        ok = (verdict["records"] >= 500
              and all(n >= 1 for n in verdict["published"].values())
              and verdict["compacted_shards"] >= 1
              and verdict["compacted_bytes"] > 0
              and verdict["alerts_fired"] == 0
              and verdict["sheds"] == 0
              and verdict["crc_ok_after_kill"])
        verdict["ok"] = bool(ok)
        print(json.dumps(verdict), flush=True)
        raise SystemExit(0 if verdict["ok"] else 1)
    finally:
        if proc.poll() is None:
            proc.kill()


if __name__ == "__main__":
    main()
