#!/usr/bin/env bash
# Tier-1 verify: the EXACT pipeline ROADMAP.md documents, so local runs
# and CI invoke the identical command.  Fast tests only (-m 'not slow');
# fault-injection and multi-process tests marked @pytest.mark.slow run in
# the full suite instead.
#
# Usage: tools/run_tier1.sh [extra pytest args...]
#        CHAOS=1 tools/run_tier1.sh   # also run the fault-matrix chaos
#                                     # suite (tools/chaos_run.sh) after
#        PERF=1 tools/run_tier1.sh    # also run the io_bench smoke lane
#                                     # (tiny synthetic imgbin, validates
#                                     # the per-stage JSON schema only —
#                                     # no flaky throughput assertions)
#        LOOP=1 tools/run_tier1.sh    # also run the closed-loop smoke:
#                                     # a real task=serve_train process,
#                                     # >=1k HTTP feedback records, the
#                                     # eval gate rejecting a poisoned
#                                     # update and publishing+reloading
#                                     # an improving one (JSON verdict)
#        TUNE=1 tools/run_tier1.sh    # also run the self-tuning smoke:
#                                     # io_bench + serve_bench --autotune
#                                     # start from deliberately bad knobs
#                                     # (1 worker / queue 1 / batch 1 /
#                                     # 1 ms window) and the controller
#                                     # must recover >= 90% of the hand-
#                                     # tuned throughput (JSON verdicts,
#                                     # schema-validated by the tools);
#                                     # both reports append to a
#                                     # perf_guard history
#        MESH=1 tools/run_tier1.sh    # also run the SPMD mesh parity
#                                     # lane: a 4-process CPU-mesh CLI
#                                     # train must produce checkpoint
#                                     # CRCs BITWISE equal to the
#                                     # single-process run of the same
#                                     # 4-device mesh (MNIST MLP conf,
#                                     # dist_shard=block, gloo
#                                     # collectives), with per-rank
#                                     # compile counts proving the step
#                                     # is ONE program (no per-replica
#                                     # re-jits); verdict JSON appends
#                                     # to a perf_guard history
#        QUANT=1 tools/run_tier1.sh   # also run the quantized-inference
#                                     # smoke: train + gated int8 export
#                                     # of the MNIST MLP (top-1 agreement
#                                     # >= 0.99 asserted), serve engine
#                                     # weight bytes >= 3.5x smaller via
#                                     # the serve_weight_bytes gauges,
#                                     # f32-vs-int8 closed-loop serve A/B
#                                     # (quant leg must not regress), and
#                                     # a quant_bench perf_guard entry
#        CRASH=1 tools/run_tier1.sh   # also run the crash-consistency
#                                     # audit: record every durable-write
#                                     # op sequence (checkpoint, publish
#                                     # pointer, feedback pages+commits,
#                                     # retention boundary) and replay
#                                     # EVERY crash-point prefix under the
#                                     # ext4-reorder model (flush/sync/
#                                     # torn variants) into a fresh dir,
#                                     # running the real recovery path and
#                                     # asserting the declared invariants
#                                     # (>=300 distinct states, zero
#                                     # violations) plus 5 named
#                                     # regression replays; the verdict
#                                     # appends to a perf_guard history
#                                     # (crash_audit flattener)
#        ELASTIC=1 tools/run_tier1.sh # also run the elastic-pod lane:
#                                     # a 4-process CPU-mesh CLI train
#                                     # has one NON-ZERO rank SIGKILLed
#                                     # mid-round; the survivors must
#                                     # rebuild as a 3-process mesh
#                                     # inside the same invocation, a
#                                     # waiting joiner grows it back to
#                                     # 4, and every checkpoint CRC
#                                     # must be BITWISE equal to a
#                                     # planned-resize run of the same
#                                     # shrink/grow schedule; rebuild
#                                     # latency + recovered throughput
#                                     # append to a perf_guard history;
#                                     # also runs the kill -9 crash-
#                                     # window check: rank 0 SIGKILLed
#                                     # between the consensus checkpoint
#                                     # tmp fsync and its rename — the
#                                     # torn tmp must be ignored and a
#                                     # continue=1 restart must resume
#                                     # from the prior CRC-valid round
#        FLEET=1 tools/run_tier1.sh   # also run the serving-fleet
#                                     # smoke: a REAL 2-replica
#                                     # task=serve fleet (CLI child
#                                     # processes) under open-loop
#                                     # burst load has one replica
#                                     # SIGKILLed mid-run — every
#                                     # non-shed request must still
#                                     # succeed, the supervisor must
#                                     # restart the dead replica in
#                                     # budget (JSON verdict via
#                                     # tools/fleet_smoke.py), plus a
#                                     # scaled-down in-process
#                                     # serve_bench --open-loop --burst
#                                     # profile; both land in a
#                                     # perf_guard history
#                                     # (fleet_bench / serve_bench)
#        WIRE=1 tools/run_tier1.sh    # also run the binary wire-format
#                                     # A/B: serve_bench --wire-ab
#                                     # drives JSON and CXB1-frame
#                                     # closed-loop legs over real HTTP
#                                     # (pooled keep-alive clients) and
#                                     # the binary plane must be
#                                     # >= 1.5x JSON req/s with BITWISE
#                                     # equal scores (doc/serving.md
#                                     # "Binary wire protocol"); the
#                                     # report appends to a perf_guard
#                                     # history (wire_bench flattener)
#        ASYNC=1 tools/run_tier1.sh   # also run the async data-parallel
#                                     # lane: a 4-process CPU-mesh CLI
#                                     # train with async_overlap=1,
#                                     # staleness=0 must write checkpoint
#                                     # CRCs BITWISE equal to the
#                                     # det_reduce synchronous run of the
#                                     # same conf/seed (the overlap is
#                                     # dispatch scheduling, not
#                                     # different arithmetic), plus a
#                                     # tiny staleness convergence A/B
#                                     # smoke (sync vs staleness=0 legs,
#                                     # schema-validated verdict JSON via
#                                     # tools/async_ab.py); the verdict
#                                     # appends to a perf_guard history
#                                     # (async_bench flattener:
#                                     # overlap_fraction higher-is-
#                                     # better, step_wall lower)
#        TENANT=1 tools/run_tier1.sh  # also run the multi-tenant loop
#                                     # smoke: a REAL task=loop_fleet
#                                     # process hosting 2 tenants on one
#                                     # device pool — per-model HTTP
#                                     # routing, a cohort-poisoned
#                                     # candidate rejected by the
#                                     # per-slice gate (cohort named,
#                                     # lineage-attributable), BOTH
#                                     # tenants publishing while the
#                                     # serve p99 alert stays silent,
#                                     # retention compacting consumed
#                                     # shards (disk bytes drop), and a
#                                     # kill -9 crash-window CRC check;
#                                     # verdict JSON appends to a
#                                     # perf_guard history (tenant_bench)
#        KERNEL=1 tools/run_tier1.sh  # also run the Pallas kernel-
#                                     # library lane: the interpret-mode
#                                     # parity suite (tests/
#                                     # test_kernels.py — all three
#                                     # kernels bit-equal to the jitted
#                                     # stock lowering on CPU) plus
#                                     # tools/kernel_ab.py --smoke (the
#                                     # bisect A/B end to end: parity
#                                     # gate, timed legs, schema-valid
#                                     # verdict JSON appended to a
#                                     # kernel_bench perf_guard history);
#                                     # the full-size CPU measurement +
#                                     # --record writes ops/kernels/
#                                     # verdicts.json, and the TPU legs
#                                     # stay queued in tpu_queue.sh
#        SDC=1 tools/run_tier1.sh     # also run the silent-data-
#                                     # corruption lane: a 4-process
#                                     # CPU-mesh CLI train has one real
#                                     # bit flipped in a live parameter
#                                     # tensor on rank 3; the fingerprint
#                                     # vote must detect it within
#                                     # integrity_every rounds, name the
#                                     # rank, quarantine it (exit 41) and
#                                     # rebuild in-process, and the
#                                     # finished run's checkpoint CRCs
#                                     # must be BITWISE equal to a clean
#                                     # run that never contained the
#                                     # corrupt rank; plus the serve
#                                     # golden-canary degrade/readmit
#                                     # walk and the <=2% fingerprint
#                                     # overhead bound; verdict JSON
#                                     # appends to a perf_guard history
#                                     # (integrity_bench flattener)
#        DSVC=1 tools/run_tier1.sh    # also run the data-service lane:
#                                     # a REAL task=data_service process
#                                     # feeds a CLI trainer whose data
#                                     # section is iter=service; its
#                                     # checkpoint CRCs must be BITWISE
#                                     # equal to the local-chain run,
#                                     # INCLUDING after the server is
#                                     # SIGKILLed mid-training and a
#                                     # replacement on the same port
#                                     # resumes the stream; two
#                                     # concurrent tenants must both
#                                     # hold parity with the shared
#                                     # chunk cache showing hit_rate > 0
#                                     # (tools/dataservice_smoke.py),
#                                     # plus the local-vs-service A/B
#                                     # (io_bench --service --smoke);
#                                     # both verdicts append to a
#                                     # perf_guard history
#                                     # (dataservice_bench flattener)
#        OBS=1 tools/run_tier1.sh     # also run the observability smoke:
#                                     # short telemetry=1 train + serve
#                                     # scrape of /metricsz + /alertz
#                                     # (alert fire/degrade/clear walked
#                                     # end to end), then schema-validate
#                                     # the exposition text (device-plane
#                                     # families pinned), alertz.json,
#                                     # telemetry.jsonl and events.jsonl
#                                     # via tools/obs_dump.py --check,
#                                     # plus a perf_guard --smoke verdict
set -o pipefail
cd "$(dirname "$0")/.."
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
  --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
  "$@" 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
if [ "${CHAOS:-0}" = "1" ]; then
  echo "=== opt-in chaos stage (CHAOS=1) ==="
  tools/chaos_run.sh || rc=1
fi
if [ "${PERF:-0}" = "1" ]; then
  echo "=== opt-in perf smoke (PERF=1) ==="
  timeout -k 10 300 env JAX_PLATFORMS=cpu \
    python tools/io_bench.py --smoke || rc=1
fi
if [ "${KERNEL:-0}" = "1" ]; then
  echo "=== opt-in Pallas kernel-library lane (KERNEL=1) ==="
  kernel_out=/tmp/_kernel_ab
  rm -rf "$kernel_out"; mkdir -p "$kernel_out"
  timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python -m pytest tests/test_kernels.py -q -m 'not slow' \
      -p no:cacheprovider -p no:xdist -p no:randomly || rc=1
  timeout -k 10 300 env JAX_PLATFORMS=cpu \
    python tools/kernel_ab.py --smoke \
      --history "$kernel_out/bench_history.jsonl" \
      --json "$kernel_out/kernel_ab.json" > /dev/null || rc=1
  echo "KERNEL lane verdict: $kernel_out/kernel_ab.json"
fi
if [ "${LOOP:-0}" = "1" ]; then
  echo "=== opt-in closed-loop smoke (LOOP=1) ==="
  timeout -k 10 300 env JAX_PLATFORMS=cpu \
    python tools/loop_smoke.py || rc=1
fi
if [ "${TUNE:-0}" = "1" ]; then
  echo "=== opt-in self-tuning smoke (TUNE=1) ==="
  tune_out=/tmp/_tune_smoke
  rm -rf "$tune_out"; mkdir -p "$tune_out"
  timeout -k 10 300 env JAX_PLATFORMS=cpu \
    python tools/io_bench.py 1024 160 --autotune \
      --json "$tune_out/io_autotune.json" || rc=1
  timeout -k 10 300 env JAX_PLATFORMS=cpu \
    python tools/serve_bench.py --autotune --autotune-seconds 20 \
      --json "$tune_out/serve_autotune.json" > /dev/null || rc=1
  timeout -k 10 60 env JAX_PLATFORMS=cpu \
    python tools/perf_guard.py --bench io_bench \
      --input "$tune_out/io_autotune.json" \
      --history "$tune_out/bench_history.jsonl" > /dev/null || rc=1
  timeout -k 10 60 env JAX_PLATFORMS=cpu \
    python tools/perf_guard.py --bench serve_bench \
      --input "$tune_out/serve_autotune.json" \
      --history "$tune_out/bench_history.jsonl" > /dev/null || rc=1
  echo "TUNE lane verdicts: $tune_out/{io,serve}_autotune.json"
fi
if [ "${MESH:-0}" = "1" ]; then
  echo "=== opt-in SPMD mesh parity lane (MESH=1) ==="
  mesh_out=/tmp/_mesh_parity
  rm -rf "$mesh_out"; mkdir -p "$mesh_out"
  # outer budget > 2x the tool's per-side --timeout (240 s each) plus
  # setup slack, so a slow-but-in-budget run is never killed mid-flight
  timeout -k 10 560 env JAX_PLATFORMS=cpu \
    python tools/mesh_parity.py --out "$mesh_out" > /dev/null || rc=1
  timeout -k 10 60 env JAX_PLATFORMS=cpu \
    python tools/perf_guard.py --bench mesh_parity \
      --input "$mesh_out/mesh_parity.json" \
      --history "$mesh_out/bench_history.jsonl" > /dev/null || rc=1
  echo "MESH lane verdict: $mesh_out/mesh_parity.json"
fi
if [ "${CRASH:-0}" = "1" ]; then
  echo "=== opt-in crash-consistency audit (CRASH=1) ==="
  crash_out=/tmp/_crash_audit
  rm -rf "$crash_out"; mkdir -p "$crash_out"
  timeout -k 10 300 env JAX_PLATFORMS=cpu \
    python tools/crash_audit.py --smoke \
      --out "$crash_out/crash_audit.json" || rc=1
  timeout -k 10 60 env JAX_PLATFORMS=cpu \
    python tools/perf_guard.py --bench crash_audit \
      --input "$crash_out/crash_audit.json" \
      --history "$crash_out/bench_history.jsonl" > /dev/null || rc=1
  echo "CRASH lane verdict: $crash_out/crash_audit.json"
fi
if [ "${ELASTIC:-0}" = "1" ]; then
  echo "=== opt-in elastic-pod lane (ELASTIC=1) ==="
  elastic_out=/tmp/_elastic_lane
  rm -rf "$elastic_out"; mkdir -p "$elastic_out"
  # outer budget > 2x the tool's per-run --timeout (420 s each) plus
  # data/conf setup slack
  timeout -k 10 880 env JAX_PLATFORMS=cpu \
    python tools/elastic_kill.py --out "$elastic_out" > /dev/null || rc=1
  timeout -k 10 60 env JAX_PLATFORMS=cpu \
    python tools/perf_guard.py --bench elastic \
      --input "$elastic_out/elastic.json" \
      --history "$elastic_out/bench_history.jsonl" > /dev/null || rc=1
  # kill -9 crash-window check: SIGKILL rank 0 between the consensus
  # checkpoint's tmp fsync and its rename, then restart with continue=1
  # (full run took ~30 s; budget covers a slow machine)
  timeout -k 10 300 env JAX_PLATFORMS=cpu \
    python tools/elastic_kill.py --kill-checkpoint \
      --out "$elastic_out" > /dev/null || rc=1
  timeout -k 10 60 env JAX_PLATFORMS=cpu \
    python tools/perf_guard.py --bench elastic_crash \
      --input "$elastic_out/elastic_crash.json" \
      --history "$elastic_out/bench_history.jsonl" > /dev/null || rc=1
  echo "ELASTIC lane verdict: $elastic_out/elastic.json $elastic_out/elastic_crash.json"
fi
if [ "${QUANT:-0}" = "1" ]; then
  echo "=== opt-in quantized-inference smoke (QUANT=1) ==="
  quant_out=/tmp/_quant_smoke
  rm -rf "$quant_out"; mkdir -p "$quant_out"
  timeout -k 10 300 env JAX_PLATFORMS=cpu \
    python tools/quant_smoke.py --out "$quant_out" \
      > "$quant_out/verdict.json" || rc=1
  timeout -k 10 60 env JAX_PLATFORMS=cpu \
    python tools/perf_guard.py --bench quant_bench \
      --input "$quant_out/verdict.json" \
      --history "$quant_out/bench_history.jsonl" > /dev/null || rc=1
  echo "QUANT lane verdict: $quant_out/verdict.json"
fi
if [ "${FLEET:-0}" = "1" ]; then
  echo "=== opt-in serving-fleet smoke (FLEET=1) ==="
  fleet_out=/tmp/_fleet_smoke
  rm -rf "$fleet_out"; mkdir -p "$fleet_out"
  timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python tools/fleet_smoke.py --out "$fleet_out" --replicas 2 \
      > "$fleet_out/verdict.json" || rc=1
  timeout -k 10 60 env JAX_PLATFORMS=cpu \
    python tools/perf_guard.py --bench fleet_bench \
      --input "$fleet_out/fleet_smoke.json" \
      --history "$fleet_out/bench_history.jsonl" > /dev/null || rc=1
  # scaled-down burst profile over the in-process engine (the full
  # >=10^6-request invocation is queued in tpu_queue.sh)
  timeout -k 10 300 env JAX_PLATFORMS=cpu \
    python tools/serve_bench.py --open-loop --burst --duration 6 \
      --base-rate 50 --burst-rate 200 --phase 1 \
      --json "$fleet_out/burst.json" > /dev/null || rc=1
  timeout -k 10 60 env JAX_PLATFORMS=cpu \
    python tools/perf_guard.py --bench serve_bench \
      --input "$fleet_out/burst.json" \
      --history "$fleet_out/bench_history.jsonl" > /dev/null || rc=1
  echo "FLEET lane verdict: $fleet_out/fleet_smoke.json"
fi
if [ "${WIRE:-0}" = "1" ]; then
  echo "=== opt-in binary wire-format A/B (WIRE=1) ==="
  wire_out=/tmp/_wire_ab
  rm -rf "$wire_out"; mkdir -p "$wire_out"
  timeout -k 10 300 env JAX_PLATFORMS=cpu \
    python tools/serve_bench.py --wire-ab --rows 32 --concurrency 8 \
      --requests 60 --max-batch 256 --timeout-ms 1 \
      --json "$wire_out/wire_ab.json" > /dev/null || rc=1
  # the hard acceptance bar: binary >= 1.5x JSON req/s, bitwise-equal
  # scores (the parity bit is also serve_bench's own exit status)
  python - "$wire_out/wire_ab.json" <<'PYEOF' || rc=1
import json, sys
ab = json.load(open(sys.argv[1]))["wire_ab"]
ok = ab["bitwise_equal_scores"] and ab["speedup"] >= 1.5
print(f"WIRE lane: speedup {ab['speedup']:.3f} (bar 1.5) parity "
      f"{'ok' if ab['bitwise_equal_scores'] else 'FAIL'}"
      f" -> {'OK' if ok else 'FAIL'}")
sys.exit(0 if ok else 1)
PYEOF
  timeout -k 10 60 env JAX_PLATFORMS=cpu \
    python tools/perf_guard.py --bench wire_bench \
      --input "$wire_out/wire_ab.json" \
      --history "$wire_out/bench_history.jsonl" > /dev/null || rc=1
  echo "WIRE lane verdict: $wire_out/wire_ab.json"
fi
if [ "${ASYNC:-0}" = "1" ]; then
  echo "=== opt-in async data-parallel lane (ASYNC=1) ==="
  async_out=/tmp/_async_lane
  rm -rf "$async_out"; mkdir -p "$async_out"
  # outer budget > the tool's per-leg --timeout (240 s) x the smoke's
  # four legs (2 parity + 2 A/B) plus data/conf setup slack
  timeout -k 10 1080 env JAX_PLATFORMS=cpu \
    python tools/async_ab.py --smoke --out "$async_out" \
      > /dev/null || rc=1
  timeout -k 10 60 env JAX_PLATFORMS=cpu \
    python tools/perf_guard.py --bench async_bench \
      --input "$async_out/async_ab.json" \
      --history "$async_out/bench_history.jsonl" > /dev/null || rc=1
  echo "ASYNC lane verdict: $async_out/async_ab.json"
fi
if [ "${TENANT:-0}" = "1" ]; then
  echo "=== opt-in multi-tenant loop smoke (TENANT=1) ==="
  tenant_out=/tmp/_tenant_smoke
  rm -rf "$tenant_out"; mkdir -p "$tenant_out"
  timeout -k 10 420 env JAX_PLATFORMS=cpu \
    python tools/tenant_smoke.py --out "$tenant_out" \
      > "$tenant_out/verdict.json" || rc=1
  timeout -k 10 60 env JAX_PLATFORMS=cpu \
    python tools/perf_guard.py --bench tenant_bench \
      --input "$tenant_out/verdict.json" \
      --history "$tenant_out/bench_history.jsonl" > /dev/null || rc=1
  echo "TENANT lane verdict: $tenant_out/verdict.json"
fi
if [ "${SDC:-0}" = "1" ]; then
  echo "=== opt-in silent-data-corruption lane (SDC=1) ==="
  sdc_out=/tmp/_sdc_lane
  rm -rf "$sdc_out"; mkdir -p "$sdc_out"
  # outer budget > 2x the tool's per-run --timeout (420 s) plus the
  # overhead run and canary walk
  timeout -k 10 1000 env JAX_PLATFORMS=cpu \
    python tools/sdc_smoke.py --out "$sdc_out" > /dev/null || rc=1
  timeout -k 10 60 env JAX_PLATFORMS=cpu \
    python tools/perf_guard.py --bench integrity_bench \
      --input "$sdc_out/sdc.json" \
      --history "$sdc_out/bench_history.jsonl" > /dev/null || rc=1
  echo "SDC lane verdict: $sdc_out/sdc.json"
fi
if [ "${DSVC:-0}" = "1" ]; then
  echo "=== opt-in data-service lane (DSVC=1) ==="
  dsvc_out=/tmp/_dsvc_lane
  rm -rf "$dsvc_out"; mkdir -p "$dsvc_out"
  # outer budget > the tool's per-leg --timeout (240 s) x four legs
  # (local, service, kill/resume, 2-tenant) plus server startup slack;
  # the full run takes ~30 s on a healthy machine
  timeout -k 10 1000 env JAX_PLATFORMS=cpu \
    python tools/dataservice_smoke.py --out "$dsvc_out" \
      > /dev/null || rc=1
  timeout -k 10 300 env JAX_PLATFORMS=cpu \
    python tools/io_bench.py --service --smoke \
      --json "$dsvc_out/dsvc_bench.json" || rc=1
  timeout -k 10 60 env JAX_PLATFORMS=cpu \
    python tools/perf_guard.py --bench dataservice_bench \
      --input "$dsvc_out/dsvc_bench.json" \
      --history "$dsvc_out/bench_history.jsonl" > /dev/null || rc=1
  echo "DSVC lane verdict: $dsvc_out/dataservice_smoke.json $dsvc_out/dsvc_bench.json"
fi
if [ "${OBS:-0}" = "1" ]; then
  echo "=== opt-in observability smoke (OBS=1) ==="
  obs_out=/tmp/_obs_smoke
  rm -rf "$obs_out"
  timeout -k 10 300 env JAX_PLATFORMS=cpu \
    python tools/obs_smoke.py --out "$obs_out" || rc=1
  timeout -k 10 60 python tools/obs_dump.py --check \
    --metrics "$obs_out/metricsz.txt" \
    --alertz "$obs_out/alertz.json" \
    --require xla_program_flops,xla_program_bytes,xla_compile_seconds_total,obs_alerts_firing \
    --telemetry "$obs_out/telemetry.jsonl" \
    --events "$obs_out/events.jsonl" || rc=1
  timeout -k 10 300 env JAX_PLATFORMS=cpu \
    python tools/perf_guard.py --smoke \
    --history "$obs_out/bench_history.jsonl" \
    --json "$obs_out/perf_verdict.json" || rc=1
fi
exit $rc
