"""Observability dump/validate tool: metrics, telemetry, event logs.

The command-line companion of ``cxxnet_tpu/obs/`` (doc/observability.md)
— and the schema gate the ``OBS=1`` lane of ``tools/run_tier1.sh``
asserts.  Three artifact kinds:

* **metrics** — Prometheus text exposition, either scraped to a file or
  fetched live (``--metrics http://host:port/metricsz``).  The
  validator checks the exposition grammar line by line: HELP/TYPE
  placement, metric/label name syntax, label-value escaping, float
  sample values, duplicate sample detection, and histogram invariants
  (cumulative non-decreasing ``le`` buckets, ``+Inf`` == ``_count``,
  ``_sum``/``_count`` present).
* **telemetry** — the per-round ``telemetry.jsonl`` a ``telemetry=1``
  train run appends (one JSON object per line with ``ts`` / ``round``
  / ``steps`` / ``eval`` / ``stages``).
* **events** — the rotating structured event log (``event_log=...``):
  one JSON object per line with ``ts`` + ``kind``.

Usage:
  python tools/obs_dump.py --check --metrics /tmp/metricsz.txt \\
      --telemetry telemetry.jsonl --events events.jsonl
  python tools/obs_dump.py --tail 20 --events events.jsonl
  python tools/obs_dump.py --summary --events events.jsonl
  python tools/obs_dump.py --summary --telemetry telemetry.jsonl

``--check`` exits non-zero on the first schema violation, printing
every problem found; ``--tail``/``--summary`` are the human front-end.
"""

from __future__ import annotations

import argparse
import json
import math
import re
import sys
from typing import Dict, List, Optional, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})?\s+(\S+)(?:\s+(-?\d+))?$"
)
_METRIC_KINDS = ("counter", "gauge", "histogram", "summary", "untyped")

#: keys every per-round telemetry record must carry
TELEMETRY_REQUIRED = ("ts", "round", "steps", "eval", "stages")
#: canonical pipeline stages every record's ``stages`` must include
TELEMETRY_STAGES = ("decode", "augment", "batch", "h2d", "device_wait")


def _parse_labels(text: str) -> Optional[Dict[str, str]]:
    """Parse ``{a="b",c="d"}``; None on malformed text (bad escapes,
    unquoted values, bad label names)."""
    if not (text.startswith("{") and text.endswith("}")):
        return None
    body = text[1:-1]
    out: Dict[str, str] = {}
    i, n = 0, len(body)
    while i < n:
        j = body.find("=", i)
        if j < 0:
            return None
        name = body[i:j].strip()
        if not _LABEL_NAME_RE.match(name):
            return None
        if j + 1 >= n or body[j + 1] != '"':
            return None
        k = j + 2
        val: List[str] = []
        while k < n:
            c = body[k]
            if c == "\\":
                if k + 1 >= n or body[k + 1] not in ('"', "\\", "n"):
                    return None
                val.append({"n": "\n"}.get(body[k + 1], body[k + 1]))
                k += 2
                continue
            if c == '"':
                break
            if c == "\n":
                return None
            val.append(c)
            k += 1
        else:
            return None
        if name in out:
            return None  # duplicate label name
        out[name] = "".join(val)
        i = k + 1
        if i < n:
            if body[i] != ",":
                return None
            i += 1
    return out


def _parse_value(text: str) -> Optional[float]:
    if text in ("+Inf", "Inf"):
        return math.inf
    if text == "-Inf":
        return -math.inf
    if text == "NaN":
        return math.nan
    try:
        return float(text)
    except ValueError:
        return None


def validate_prometheus_text(text: str) -> List[str]:
    """Return a list of problems (empty == valid exposition text)."""
    problems: List[str] = []
    types: Dict[str, str] = {}
    seen_samples: set = set()
    samples: List[Tuple[str, Dict[str, str], float]] = []
    if text and not text.endswith("\n"):
        problems.append("exposition must end with a newline")
    for ln, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            parts = line.split(" ", 3)
            if len(parts) < 3 or not _NAME_RE.match(parts[2]):
                problems.append(f"line {ln}: malformed HELP: {line!r}")
            continue
        if line.startswith("# TYPE "):
            parts = line.split(" ")
            if len(parts) != 4 or not _NAME_RE.match(parts[2]):
                problems.append(f"line {ln}: malformed TYPE: {line!r}")
                continue
            name, kind = parts[2], parts[3]
            if kind not in _METRIC_KINDS:
                problems.append(f"line {ln}: unknown metric kind {kind!r}")
            if name in types:
                problems.append(f"line {ln}: duplicate TYPE for {name}")
            types[name] = kind
            continue
        if line.startswith("#"):
            continue  # comment
        m = _SAMPLE_RE.match(line)
        if not m:
            problems.append(f"line {ln}: unparseable sample: {line!r}")
            continue
        name, labeltext, valtext = m.group(1), m.group(2), m.group(3)
        labels = _parse_labels(labeltext) if labeltext else {}
        if labels is None:
            problems.append(f"line {ln}: malformed labels: {labeltext!r}")
            continue
        value = _parse_value(valtext)
        if value is None:
            problems.append(f"line {ln}: bad sample value {valtext!r}")
            continue
        key = (name, tuple(sorted(labels.items())))
        if key in seen_samples:
            problems.append(f"line {ln}: duplicate sample {line!r}")
        seen_samples.add(key)
        samples.append((name, labels, value))
    # histogram invariants per family and labelset (excluding 'le')
    hist_names = {n for n, k in types.items() if k == "histogram"}
    for base in sorted(hist_names):
        buckets: Dict[Tuple, List[Tuple[float, float]]] = {}
        sums: Dict[Tuple, float] = {}
        counts: Dict[Tuple, float] = {}
        for name, labels, value in samples:
            rest = tuple(sorted(
                (k, v) for k, v in labels.items() if k != "le"
            ))
            if name == base + "_bucket":
                if "le" not in labels:
                    problems.append(f"{base}: bucket sample without le")
                    continue
                le = _parse_value(labels["le"])
                if le is None:
                    problems.append(
                        f"{base}: unparseable le {labels['le']!r}")
                    continue
                buckets.setdefault(rest, []).append((le, value))
            elif name == base + "_sum":
                sums[rest] = value
            elif name == base + "_count":
                counts[rest] = value
        if not buckets:
            problems.append(f"{base}: histogram with no _bucket samples")
        for rest, bl in buckets.items():
            bl.sort()
            vals = [v for _, v in bl]
            if any(vals[i + 1] < vals[i] for i in range(len(vals) - 1)):
                problems.append(
                    f"{base}{dict(rest)}: buckets not cumulative")
            if not bl or not math.isinf(bl[-1][0]):
                problems.append(f"{base}{dict(rest)}: missing +Inf bucket")
            if rest not in sums or rest not in counts:
                problems.append(f"{base}{dict(rest)}: missing _sum/_count")
            elif bl and math.isinf(bl[-1][0]) and bl[-1][1] != counts[rest]:
                problems.append(
                    f"{base}{dict(rest)}: +Inf bucket {bl[-1][1]} != "
                    f"_count {counts[rest]}")
    return problems


def _read_jsonl(path: str) -> List[Tuple[int, object]]:
    out = []
    with open(path, "r", encoding="utf-8") as f:
        for ln, line in enumerate(f, 1):
            if line.strip():
                out.append((ln, json.loads(line)))
    return out


def validate_telemetry(path: str) -> List[str]:
    """Schema-check a ``telemetry.jsonl``; returns problems (empty=ok)."""
    problems: List[str] = []
    try:
        rows = _read_jsonl(path)
    except (OSError, ValueError) as e:
        return [f"{path}: {type(e).__name__}: {e}"]
    if not rows:
        return [f"{path}: no telemetry records"]
    last_round = None
    for ln, rec in rows:
        if not isinstance(rec, dict):
            problems.append(f"line {ln}: not an object")
            continue
        for key in TELEMETRY_REQUIRED:
            if key not in rec:
                problems.append(f"line {ln}: missing key {key!r}")
        if not isinstance(rec.get("stages"), dict):
            problems.append(f"line {ln}: stages is not an object")
        else:
            for st in TELEMETRY_STAGES:
                if st not in rec["stages"]:
                    problems.append(f"line {ln}: missing stage {st!r}")
        if not isinstance(rec.get("eval"), dict):
            problems.append(f"line {ln}: eval is not an object")
        r = rec.get("round")
        if isinstance(r, int):
            if last_round is not None and r < last_round:
                problems.append(
                    f"line {ln}: round went backwards ({last_round}->{r})")
            last_round = r
        else:
            problems.append(f"line {ln}: round is not an int")
    return problems


def validate_events(path: str) -> List[str]:
    """Schema-check an event log; returns problems (empty == valid)."""
    problems: List[str] = []
    try:
        rows = _read_jsonl(path)
    except (OSError, ValueError) as e:
        return [f"{path}: {type(e).__name__}: {e}"]
    if not rows:
        return [f"{path}: no events"]
    for ln, rec in rows:
        if not isinstance(rec, dict):
            problems.append(f"line {ln}: not an object")
            continue
        if not isinstance(rec.get("ts"), (int, float)):
            problems.append(f"line {ln}: missing/bad ts")
        if not (isinstance(rec.get("kind"), str) and rec["kind"]):
            problems.append(f"line {ln}: missing/bad kind")
    return problems


# ----------------------------------------------------------------------
# human front-end
def _load_metrics_text(src: str) -> str:
    if src.startswith(("http://", "https://")):
        import urllib.request

        with urllib.request.urlopen(src, timeout=10) as r:
            return r.read().decode("utf-8")
    with open(src, "r", encoding="utf-8") as f:
        return f.read()


def _tail(path: str, n: int) -> None:
    rows = _read_jsonl(path)
    for _, rec in rows[-n:]:
        print(json.dumps(rec, sort_keys=True))


def _summarize_events(path: str) -> None:
    counts: Dict[str, int] = {}
    first = last = None
    for _, rec in _read_jsonl(path):
        k = rec.get("kind", "?")
        counts[k] = counts.get(k, 0) + 1
        ts = rec.get("ts")
        if isinstance(ts, (int, float)):
            first = ts if first is None else min(first, ts)
            last = ts if last is None else max(last, ts)
    span = (last - first) if first is not None else 0.0
    print(f"{sum(counts.values())} event(s) over {span:.1f}s:")
    for k in sorted(counts, key=counts.get, reverse=True):
        print(f"  {counts[k]:6d}  {k}")


def _summarize_telemetry(path: str) -> None:
    rows = [rec for _, rec in _read_jsonl(path)]
    print(f"{len(rows)} round record(s)")
    if not rows:
        return
    hdr = f"{'round':>6} {'steps':>6} {'step_ms':>9} {'samp/s':>9}  eval"
    print(hdr)
    for rec in rows:
        step = rec.get("step") or {}
        ev = rec.get("eval") or {}
        evtxt = " ".join(f"{k}={v:g}" for k, v in sorted(ev.items()))
        print(f"{rec.get('round', -1):>6} {rec.get('steps', 0):>6} "
              f"{step.get('mean_ms', 0.0):>9.2f} "
              f"{step.get('samples_per_sec', 0.0):>9.1f}  {evtxt}")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--check", action="store_true",
                    help="schema-validate the given artifacts; exit 1 on "
                         "any violation")
    ap.add_argument("--metrics", default="",
                    help="Prometheus exposition text: file path or URL")
    ap.add_argument("--telemetry", default="",
                    help="per-round telemetry.jsonl path")
    ap.add_argument("--events", default="", help="event-log JSONL path")
    ap.add_argument("--tail", type=int, default=0,
                    help="print the last N records of --events/--telemetry")
    ap.add_argument("--summary", action="store_true",
                    help="aggregate the given --events/--telemetry")
    args = ap.parse_args()

    if not (args.metrics or args.telemetry or args.events):
        ap.error("give at least one of --metrics/--telemetry/--events")
    if (args.tail or args.summary) and not (args.events or args.telemetry):
        ap.error("--tail/--summary need --events or --telemetry")

    if args.check:
        problems: List[str] = []
        if args.metrics:
            try:
                text = _load_metrics_text(args.metrics)
            except OSError as e:
                problems.append(f"metrics {args.metrics}: {e}")
            else:
                probs = validate_prometheus_text(text)
                problems += [f"metrics: {p}" for p in probs]
                if not probs:
                    n = sum(1 for l in text.splitlines()
                            if l and not l.startswith("#"))
                    print(f"metrics: OK ({n} samples)")
        if args.telemetry:
            probs = validate_telemetry(args.telemetry)
            problems += [f"telemetry: {p}" for p in probs]
            if not probs:
                print("telemetry: OK")
        if args.events:
            probs = validate_events(args.events)
            problems += [f"events: {p}" for p in probs]
            if not probs:
                print("events: OK")
        for p in problems:
            print(f"FAIL {p}", file=sys.stderr)
        return 1 if problems else 0

    if args.tail:
        _tail(args.events or args.telemetry, args.tail)
        return 0
    if args.summary:
        if args.events:
            _summarize_events(args.events)
        if args.telemetry:
            _summarize_telemetry(args.telemetry)
        return 0
    # default view: summarize whatever was given
    if args.metrics:
        print(_load_metrics_text(args.metrics), end="")
    if args.events:
        _summarize_events(args.events)
    if args.telemetry:
        _summarize_telemetry(args.telemetry)
    return 0


if __name__ == "__main__":
    sys.exit(main())
