"""Observability dump/validate tool: metrics, telemetry, event logs.

The command-line companion of ``cxxnet_tpu/obs/`` (doc/observability.md)
— and the schema gate the ``OBS=1`` lane of ``tools/run_tier1.sh``
asserts.  Three artifact kinds:

* **metrics** — Prometheus text exposition, either scraped to a file or
  fetched live (``--metrics http://host:port/metricsz``).  The
  validator checks the exposition grammar line by line: HELP/TYPE
  placement, metric/label name syntax, label-value escaping, float
  sample values, duplicate sample detection, and histogram invariants
  (cumulative non-decreasing ``le`` buckets, ``+Inf`` == ``_count``,
  ``_sum``/``_count`` present).
* **telemetry** — the per-round ``telemetry.jsonl`` a ``telemetry=1``
  train run appends (one JSON object per line with ``ts`` / ``round``
  / ``steps`` / ``eval`` / ``stages``).
* **events** — the rotating structured event log (``event_log=...``):
  one JSON object per line with ``ts`` + ``kind``.
* **alertz** — the ``GET /alertz`` JSON body (``--alertz`` file or
  URL): configured rules with live firing state.
* **healthz** — a ``GET /healthz`` JSON body (``--healthz`` file or
  URL), single engine or fleet aggregate: closed status vocabulary
  plus the machine-readable ``reasons`` token list the fleet
  supervisor's probe parses (doc/serving.md "Serving fleet").

``--require fam1,fam2`` additionally asserts that the exposition text
carries those metric families — how the CI lane pins the device-plane
families (``xla_program_flops``, ``xla_compile_seconds_total``, ...).

``--lineage MODEL_DIR [--feedback DIR]`` answers "which requests
trained the model now serving": reads ``PUBLISHED.json``'s lineage
block (feedback-record id range + counts) and, given the feedback log
directory, resolves the range to the committed pages/shards holding
those records.

Usage:
  python tools/obs_dump.py --check --metrics /tmp/metricsz.txt \\
      --telemetry telemetry.jsonl --events events.jsonl \\
      --alertz /tmp/alertz.json --require xla_program_flops
  python tools/obs_dump.py --tail 20 --events events.jsonl
  python tools/obs_dump.py --summary --events events.jsonl
  python tools/obs_dump.py --lineage models/ --feedback loop/feedback

``--check`` exits non-zero on the first schema violation, printing
every problem found; ``--tail``/``--summary`` are the human front-end.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})?\s+(\S+)(?:\s+(-?\d+))?$"
)
_METRIC_KINDS = ("counter", "gauge", "histogram", "summary", "untyped")

#: keys every per-round telemetry record must carry
TELEMETRY_REQUIRED = ("ts", "round", "steps", "eval", "stages")
#: canonical pipeline stages every record's ``stages`` must include
TELEMETRY_STAGES = ("decode", "augment", "batch", "h2d", "device_wait")


def _parse_labels(text: str) -> Optional[Dict[str, str]]:
    """Parse ``{a="b",c="d"}``; None on malformed text (bad escapes,
    unquoted values, bad label names)."""
    if not (text.startswith("{") and text.endswith("}")):
        return None
    body = text[1:-1]
    out: Dict[str, str] = {}
    i, n = 0, len(body)
    while i < n:
        j = body.find("=", i)
        if j < 0:
            return None
        name = body[i:j].strip()
        if not _LABEL_NAME_RE.match(name):
            return None
        if j + 1 >= n or body[j + 1] != '"':
            return None
        k = j + 2
        val: List[str] = []
        while k < n:
            c = body[k]
            if c == "\\":
                if k + 1 >= n or body[k + 1] not in ('"', "\\", "n"):
                    return None
                val.append({"n": "\n"}.get(body[k + 1], body[k + 1]))
                k += 2
                continue
            if c == '"':
                break
            if c == "\n":
                return None
            val.append(c)
            k += 1
        else:
            return None
        if name in out:
            return None  # duplicate label name
        out[name] = "".join(val)
        i = k + 1
        if i < n:
            if body[i] != ",":
                return None
            i += 1
    return out


def _parse_value(text: str) -> Optional[float]:
    if text in ("+Inf", "Inf"):
        return math.inf
    if text == "-Inf":
        return -math.inf
    if text == "NaN":
        return math.nan
    try:
        return float(text)
    except ValueError:
        return None


def validate_prometheus_text(text: str) -> List[str]:
    """Return a list of problems (empty == valid exposition text)."""
    problems: List[str] = []
    types: Dict[str, str] = {}
    seen_samples: set = set()
    samples: List[Tuple[str, Dict[str, str], float]] = []
    if text and not text.endswith("\n"):
        problems.append("exposition must end with a newline")
    for ln, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            parts = line.split(" ", 3)
            if len(parts) < 3 or not _NAME_RE.match(parts[2]):
                problems.append(f"line {ln}: malformed HELP: {line!r}")
            continue
        if line.startswith("# TYPE "):
            parts = line.split(" ")
            if len(parts) != 4 or not _NAME_RE.match(parts[2]):
                problems.append(f"line {ln}: malformed TYPE: {line!r}")
                continue
            name, kind = parts[2], parts[3]
            if kind not in _METRIC_KINDS:
                problems.append(f"line {ln}: unknown metric kind {kind!r}")
            if name in types:
                problems.append(f"line {ln}: duplicate TYPE for {name}")
            types[name] = kind
            continue
        if line.startswith("#"):
            continue  # comment
        m = _SAMPLE_RE.match(line)
        if not m:
            problems.append(f"line {ln}: unparseable sample: {line!r}")
            continue
        name, labeltext, valtext = m.group(1), m.group(2), m.group(3)
        labels = _parse_labels(labeltext) if labeltext else {}
        if labels is None:
            problems.append(f"line {ln}: malformed labels: {labeltext!r}")
            continue
        value = _parse_value(valtext)
        if value is None:
            problems.append(f"line {ln}: bad sample value {valtext!r}")
            continue
        key = (name, tuple(sorted(labels.items())))
        if key in seen_samples:
            problems.append(f"line {ln}: duplicate sample {line!r}")
        seen_samples.add(key)
        samples.append((name, labels, value))
    # histogram invariants per family and labelset (excluding 'le')
    hist_names = {n for n, k in types.items() if k == "histogram"}
    for base in sorted(hist_names):
        buckets: Dict[Tuple, List[Tuple[float, float]]] = {}
        sums: Dict[Tuple, float] = {}
        counts: Dict[Tuple, float] = {}
        for name, labels, value in samples:
            rest = tuple(sorted(
                (k, v) for k, v in labels.items() if k != "le"
            ))
            if name == base + "_bucket":
                if "le" not in labels:
                    problems.append(f"{base}: bucket sample without le")
                    continue
                le = _parse_value(labels["le"])
                if le is None:
                    problems.append(
                        f"{base}: unparseable le {labels['le']!r}")
                    continue
                buckets.setdefault(rest, []).append((le, value))
            elif name == base + "_sum":
                sums[rest] = value
            elif name == base + "_count":
                counts[rest] = value
        if not buckets:
            problems.append(f"{base}: histogram with no _bucket samples")
        for rest, bl in buckets.items():
            bl.sort()
            vals = [v for _, v in bl]
            if any(vals[i + 1] < vals[i] for i in range(len(vals) - 1)):
                problems.append(
                    f"{base}{dict(rest)}: buckets not cumulative")
            if not bl or not math.isinf(bl[-1][0]):
                problems.append(f"{base}{dict(rest)}: missing +Inf bucket")
            if rest not in sums or rest not in counts:
                problems.append(f"{base}{dict(rest)}: missing _sum/_count")
            elif bl and math.isinf(bl[-1][0]) and bl[-1][1] != counts[rest]:
                problems.append(
                    f"{base}{dict(rest)}: +Inf bucket {bl[-1][1]} != "
                    f"_count {counts[rest]}")
    return problems


def _read_jsonl(path: str) -> List[Tuple[int, object]]:
    out = []
    with open(path, "r", encoding="utf-8") as f:
        for ln, line in enumerate(f, 1):
            if line.strip():
                out.append((ln, json.loads(line)))
    return out


def validate_telemetry(path: str) -> List[str]:
    """Schema-check a ``telemetry.jsonl``; returns problems (empty=ok)."""
    problems: List[str] = []
    try:
        rows = _read_jsonl(path)
    except (OSError, ValueError) as e:
        return [f"{path}: {type(e).__name__}: {e}"]
    if not rows:
        return [f"{path}: no telemetry records"]
    last_round = None
    for ln, rec in rows:
        if not isinstance(rec, dict):
            problems.append(f"line {ln}: not an object")
            continue
        for key in TELEMETRY_REQUIRED:
            if key not in rec:
                problems.append(f"line {ln}: missing key {key!r}")
        if not isinstance(rec.get("stages"), dict):
            problems.append(f"line {ln}: stages is not an object")
        else:
            for st in TELEMETRY_STAGES:
                if st not in rec["stages"]:
                    problems.append(f"line {ln}: missing stage {st!r}")
        if not isinstance(rec.get("eval"), dict):
            problems.append(f"line {ln}: eval is not an object")
        r = rec.get("round")
        if isinstance(r, int):
            if last_round is not None and r < last_round:
                problems.append(
                    f"line {ln}: round went backwards ({last_round}->{r})")
            last_round = r
        else:
            problems.append(f"line {ln}: round is not an int")
    return problems


def exposition_families(text: str) -> set:
    """Family names present in an exposition text: TYPE declarations
    plus bare sample names (suffix-stripped for histogram parts)."""
    fams = set()
    for line in text.splitlines():
        if line.startswith("# TYPE "):
            parts = line.split(" ")
            if len(parts) >= 3:
                fams.add(parts[2])
            continue
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m:
            name = m.group(1)
            fams.add(name)
            for suffix in ("_bucket", "_sum", "_count"):
                if name.endswith(suffix):
                    fams.add(name[: -len(suffix)])
    return fams


_ALERT_STATES = ("ok", "pending", "firing")
_ALERT_RULE_KEYS = ("name", "metric", "op", "threshold", "for_s", "state")


def validate_alertz(obj) -> List[str]:
    """Schema-check a ``GET /alertz`` body; returns problems."""
    problems: List[str] = []
    if not isinstance(obj, dict):
        return ["alertz: body is not an object"]
    for key in ("period_s", "rules", "firing"):
        if key not in obj:
            problems.append(f"alertz: missing key {key!r}")
    rules = obj.get("rules")
    if not isinstance(rules, list):
        problems.append("alertz: rules is not a list")
        rules = []
    names = set()
    for i, rule in enumerate(rules):
        if not isinstance(rule, dict):
            problems.append(f"alertz: rule[{i}] is not an object")
            continue
        for key in _ALERT_RULE_KEYS:
            if key not in rule:
                problems.append(f"alertz: rule[{i}] missing {key!r}")
        if rule.get("state") not in _ALERT_STATES:
            problems.append(
                f"alertz: rule[{i}] bad state {rule.get('state')!r}")
        if rule.get("op") not in (">", "<", ">=", "<="):
            problems.append(f"alertz: rule[{i}] bad op {rule.get('op')!r}")
        names.add(rule.get("name"))
    firing = obj.get("firing")
    if not isinstance(firing, list):
        problems.append("alertz: firing is not a list")
    else:
        # str() both sides: a malformed rule with no name must yield a
        # reported problem, not a None-vs-str sort TypeError
        expect = sorted(str(r.get("name")) for r in rules
                        if isinstance(r, dict)
                        and r.get("state") == "firing")
        if sorted(str(n) for n in firing) != expect:
            problems.append(
                f"alertz: firing {firing} inconsistent with rule "
                f"states {expect}")
    return problems


_HEALTH_STATUSES = ("ok", "degraded", "down", "closed")

#: stable engine degrade-reason tokens (doc/serving.md;
#: ``integrity_failed`` = golden-canary drift, doc/robustness.md
#: "Integrity plane").  ``alert:<rule>`` rides alongside for firing
#: alert rules; fleet aggregates prefix every token ``replica<i>:``
#: and additionally emit out-of-rotation replica STATES.
_HEALTH_REASON_TOKENS = ("reload_breaker_open", "mesh_rebuilding",
                         "integrity_failed")
_HEALTH_REPLICA_STATES = ("starting", "slow", "quarantined", "wedged",
                          "gone", "backoff", "failed")


def _reason_token_ok(tok: str) -> bool:
    base = tok
    m = re.match(r"^replica\d+:(.+)$", tok)
    if m:
        base = m.group(1)
        if base in _HEALTH_REPLICA_STATES:
            return True
    if base in _HEALTH_REASON_TOKENS:
        return True
    return base.startswith("alert:") and len(base) > len("alert:")


def validate_healthz(obj) -> List[str]:
    """Schema-check a ``GET /healthz`` body — single engine or fleet
    aggregate.  The machine-readable contract the fleet supervisor's
    probe (and any external load balancer) parses: a ``status`` from
    the closed vocabulary plus a ``reasons`` list of stable string
    tokens spelling out every degrade condition
    (``serve/engine.py::healthz``, ``serve/fleet.py::healthz``)."""
    problems: List[str] = []
    if not isinstance(obj, dict):
        return ["body is not an object"]
    status = obj.get("status")
    if status not in _HEALTH_STATUSES:
        problems.append(f"bad status {status!r} (want one of "
                        f"{'/'.join(_HEALTH_STATUSES)})")
    reasons = obj.get("reasons")
    if not isinstance(reasons, list) or any(
            not isinstance(x, str) for x in reasons):
        problems.append("reasons must be a list of strings")
    else:
        if status == "degraded" and not reasons:
            problems.append(
                "degraded with an empty reasons list (every degrade "
                "condition must carry a machine-readable token)")
        if status == "ok" and reasons:
            problems.append(f"ok but reasons non-empty: {reasons}")
        unknown = [t for t in reasons if not _reason_token_ok(t)]
        if unknown:
            problems.append(
                f"unknown reason token(s) {unknown} (want "
                f"{'/'.join(_HEALTH_REASON_TOKENS)}, alert:<rule>, or "
                "a replica<i>:-prefixed engine token / out-of-rotation "
                "state)")
    if not isinstance(obj.get("round"), int):
        problems.append("round missing or not an integer")
    if obj.get("fleet"):
        reps = obj.get("replicas")
        if not isinstance(reps, dict) or not isinstance(
                reps.get("total"), int):
            problems.append("fleet body needs a replicas object with "
                            "an integer total")
        elif any(not isinstance(v, int) for v in reps.values()):
            problems.append("replicas state counts must be integers")
        if not isinstance(obj.get("rotation"), int):
            problems.append("fleet body needs an integer rotation")
    else:
        # the pre-fleet fields stay alongside reasons (compat contract)
        for key in ("model", "reload_breaker"):
            if key not in obj:
                problems.append(f"missing legacy key {key!r}")
    return problems


def validate_events(path: str) -> List[str]:
    """Schema-check an event log; returns problems (empty == valid)."""
    problems: List[str] = []
    try:
        rows = _read_jsonl(path)
    except (OSError, ValueError) as e:
        return [f"{path}: {type(e).__name__}: {e}"]
    if not rows:
        return [f"{path}: no events"]
    for ln, rec in rows:
        if not isinstance(rec, dict):
            problems.append(f"line {ln}: not an object")
            continue
        if not isinstance(rec.get("ts"), (int, float)):
            problems.append(f"line {ln}: missing/bad ts")
        if not (isinstance(rec.get("kind"), str) and rec["kind"]):
            problems.append(f"line {ln}: missing/bad kind")
    return problems


# ----------------------------------------------------------------------
# lineage: PUBLISHED.json -> feedback-log pages
_SHARD_COMMIT_RE = re.compile(r"^feedback-(\d{6})\.bin\.commit$")


def _feedback_pages(feedback_dir: str) -> List[Tuple[int, Dict]]:
    """All committed page entries ``(shard_idx, entry)`` across the
    log's ``.commit`` sidecars, shard order (same trust rules as the
    reader: stop a shard at the first torn/foreign line)."""
    out: List[Tuple[int, Dict]] = []
    try:
        names = sorted(os.listdir(feedback_dir))
    except OSError:
        return out
    for n in names:
        m = _SHARD_COMMIT_RE.match(n)
        if not m:
            continue
        idx = int(m.group(1))
        try:
            with open(os.path.join(feedback_dir, n), "r",
                      encoding="utf-8") as f:
                text = f.read()
        except OSError:
            continue
        for line in text.split("\n"):
            line = line.strip()
            if not line:
                continue
            try:
                ent = json.loads(line)
            except ValueError:
                break
            # same required keys as FeedbackReader._read_commits — an
            # entry the reader would refuse must not count as trained-on
            if isinstance(ent, dict) and {"off", "bytes", "crc32",
                                          "nrec"} <= set(ent):
                out.append((idx, ent))
            else:
                break
    return out


def resolve_lineage(model_dir: str,
                    feedback_dir: str = "") -> Tuple[dict, List[str]]:
    """Answer "which requests trained the published model": the publish
    pointer's lineage block, plus (with the feedback-log dir) the
    committed pages covering the id range.  Returns ``(report,
    problems)`` — problems non-empty when the chain does not resolve."""
    problems: List[str] = []
    ptr_path = os.path.join(model_dir, "PUBLISHED.json")
    try:
        with open(ptr_path, "r", encoding="utf-8") as f:
            ptr = json.load(f)
    except (OSError, ValueError) as e:
        return {}, [f"lineage: cannot read {ptr_path}: {e}"]
    report = {
        "round": ptr.get("round"),
        "path": ptr.get("path"),
        "metric": ptr.get("metric"),
        "published_ts": ptr.get("time"),
        "lineage": ptr.get("lineage"),
    }
    lin = ptr.get("lineage")
    if not isinstance(lin, dict):
        problems.append(
            f"lineage: {ptr_path} carries no lineage block (published "
            "before the lineage format, or by a bare write)")
        return report, problems
    first, last = lin.get("first_seq"), lin.get("last_seq")
    if feedback_dir and first is not None and last is not None:
        pages = []
        covered = 0
        for idx, ent in _feedback_pages(feedback_dir):
            s0 = ent.get("seq0")
            if s0 is None:
                continue
            lo, hi = int(s0), int(s0) + int(ent["nrec"]) - 1
            if hi < first or lo > last:
                continue
            overlap = min(hi, last) - max(lo, first) + 1
            covered += overlap
            pages.append({"shard": idx, "off": ent["off"],
                          "seq": [lo, hi], "overlap": overlap})
        report["resolved"] = {
            "feedback_dir": feedback_dir,
            "pages": pages,
            "records_in_range": covered,
        }
        if not pages:
            problems.append(
                f"lineage: no committed page in {feedback_dir} covers "
                f"seq range [{first}, {last}]")
    return report, problems


# ----------------------------------------------------------------------
# human front-end
def _load_metrics_text(src: str) -> str:
    if src.startswith(("http://", "https://")):
        import urllib.request

        with urllib.request.urlopen(src, timeout=10) as r:
            return r.read().decode("utf-8")
    with open(src, "r", encoding="utf-8") as f:
        return f.read()


def _load_json_obj(src: str):
    return json.loads(_load_metrics_text(src))


def _tail(path: str, n: int) -> None:
    rows = _read_jsonl(path)
    for _, rec in rows[-n:]:
        print(json.dumps(rec, sort_keys=True))


def _summarize_events(path: str) -> None:
    counts: Dict[str, int] = {}
    first = last = None
    for _, rec in _read_jsonl(path):
        k = rec.get("kind", "?")
        counts[k] = counts.get(k, 0) + 1
        ts = rec.get("ts")
        if isinstance(ts, (int, float)):
            first = ts if first is None else min(first, ts)
            last = ts if last is None else max(last, ts)
    span = (last - first) if first is not None else 0.0
    print(f"{sum(counts.values())} event(s) over {span:.1f}s:")
    for k in sorted(counts, key=counts.get, reverse=True):
        print(f"  {counts[k]:6d}  {k}")


def _summarize_telemetry(path: str) -> None:
    rows = [rec for _, rec in _read_jsonl(path)]
    print(f"{len(rows)} round record(s)")
    if not rows:
        return
    hdr = f"{'round':>6} {'steps':>6} {'step_ms':>9} {'samp/s':>9}  eval"
    print(hdr)
    for rec in rows:
        step = rec.get("step") or {}
        ev = rec.get("eval") or {}
        evtxt = " ".join(f"{k}={v:g}" for k, v in sorted(ev.items()))
        print(f"{rec.get('round', -1):>6} {rec.get('steps', 0):>6} "
              f"{step.get('mean_ms', 0.0):>9.2f} "
              f"{step.get('samples_per_sec', 0.0):>9.1f}  {evtxt}")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--check", action="store_true",
                    help="schema-validate the given artifacts; exit 1 on "
                         "any violation")
    ap.add_argument("--metrics", default="",
                    help="Prometheus exposition text: file path or URL")
    ap.add_argument("--telemetry", default="",
                    help="per-round telemetry.jsonl path")
    ap.add_argument("--events", default="", help="event-log JSONL path")
    ap.add_argument("--alertz", default="",
                    help="GET /alertz JSON body: file path or URL")
    ap.add_argument("--healthz", default="",
                    help="GET /healthz JSON body (engine or fleet): "
                         "file path or URL")
    ap.add_argument("--require", default="",
                    help="comma-separated metric families the exposition "
                         "must carry (device-plane pinning)")
    ap.add_argument("--lineage", default="",
                    help="model_dir: resolve PUBLISHED.json's "
                         "contributing-feedback lineage")
    ap.add_argument("--feedback", default="",
                    help="feedback-log dir for --lineage page resolution")
    ap.add_argument("--tail", type=int, default=0,
                    help="print the last N records of --events/--telemetry")
    ap.add_argument("--summary", action="store_true",
                    help="aggregate the given --events/--telemetry")
    args = ap.parse_args()

    if args.lineage:
        report, problems = resolve_lineage(args.lineage, args.feedback)
        print(json.dumps(report, indent=1))
        for p in problems:
            print(f"FAIL {p}", file=sys.stderr)
        return 1 if problems else 0

    if not (args.metrics or args.telemetry or args.events or args.alertz
            or args.healthz):
        ap.error("give at least one of --metrics/--telemetry/--events/"
                 "--alertz/--healthz (or --lineage)")
    if (args.tail or args.summary) and not (args.events or args.telemetry):
        ap.error("--tail/--summary need --events or --telemetry")

    if args.check:
        problems: List[str] = []
        if args.metrics:
            try:
                text = _load_metrics_text(args.metrics)
            except OSError as e:
                problems.append(f"metrics {args.metrics}: {e}")
            else:
                probs = validate_prometheus_text(text)
                if args.require:
                    fams = exposition_families(text)
                    for need in args.require.split(","):
                        need = need.strip()
                        if need and need not in fams:
                            probs.append(
                                f"required family {need!r} absent")
                problems += [f"metrics: {p}" for p in probs]
                if not probs:
                    n = sum(1 for l in text.splitlines()
                            if l and not l.startswith("#"))
                    print(f"metrics: OK ({n} samples)")
        if args.alertz:
            try:
                obj = _load_json_obj(args.alertz)
            except (OSError, ValueError) as e:
                problems.append(f"alertz {args.alertz}: {e}")
            else:
                probs = validate_alertz(obj)
                problems += [f"alertz: {p}" for p in probs]
                if not probs:
                    print(f"alertz: OK ({len(obj.get('rules', []))} "
                          f"rule(s), {len(obj.get('firing', []))} firing)")
        if args.healthz:
            try:
                obj = _load_json_obj(args.healthz)
            except (OSError, ValueError) as e:
                problems.append(f"healthz {args.healthz}: {e}")
            else:
                probs = validate_healthz(obj)
                problems += [f"healthz: {p}" for p in probs]
                if not probs:
                    kind = "fleet" if obj.get("fleet") else "engine"
                    print(f"healthz: OK ({kind}, status "
                          f"{obj.get('status')}, "
                          f"{len(obj.get('reasons', []))} reason(s))")
        if args.telemetry:
            probs = validate_telemetry(args.telemetry)
            problems += [f"telemetry: {p}" for p in probs]
            if not probs:
                print("telemetry: OK")
        if args.events:
            probs = validate_events(args.events)
            problems += [f"events: {p}" for p in probs]
            if not probs:
                print("events: OK")
        for p in problems:
            print(f"FAIL {p}", file=sys.stderr)
        return 1 if problems else 0

    if args.tail:
        _tail(args.events or args.telemetry, args.tail)
        return 0
    if args.summary:
        if args.events:
            _summarize_events(args.events)
        if args.telemetry:
            _summarize_telemetry(args.telemetry)
        return 0
    # default view: summarize whatever was given
    if args.metrics:
        print(_load_metrics_text(args.metrics), end="")
    if args.alertz:
        print(json.dumps(_load_json_obj(args.alertz), indent=1))
    if args.healthz:
        print(json.dumps(_load_json_obj(args.healthz), indent=1))
    if args.events:
        _summarize_events(args.events)
    if args.telemetry:
        _summarize_telemetry(args.telemetry)
    return 0


if __name__ == "__main__":
    sys.exit(main())
