#!/bin/bash
# Relay watcher (VERDICT r4 Weak #5): poll the axon relay port and fire
# the serialized measurement queue the moment it answers, so a short
# relay window is never missed between builder turns.
#
# Usage: bash tools/relay_watch.sh [logfile]   (run it in background)
# Exits after ONE queue run; re-launch to watch for another window.
set -u
cd "$(dirname "$0")/.."
LOG=${1:-/tmp/relay_watch.log}
PORT=${AXON_RELAY_PORT:-8082}
{
  echo "[relay_watch] start $(date -u +%FT%TZ) port=$PORT"
  while :; do
    until timeout 3 bash -c "echo > /dev/tcp/127.0.0.1/$PORT" 2>/dev/null; do
      sleep "${RELAY_WATCH_INTERVAL:-120}"
    done
    echo "[relay_watch] relay UP $(date -u +%FT%TZ) — firing tpu_queue"
    bash tools/tpu_queue.sh /tmp/tpu_queue.log
    rc=$?
    echo "[relay_watch] queue done rc=$rc $(date -u +%FT%TZ)"
    # rc=1 (flock held by a manual run) or rc=2 (relay died between the
    # probe and the queue's own probe): the window is NOT consumed —
    # re-enter the wait loop instead of abandoning the watch
    [ "$rc" -eq 0 ] && break
    sleep "${RELAY_WATCH_INTERVAL:-120}"
  done
} >>"$LOG" 2>&1
