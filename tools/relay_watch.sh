#!/bin/bash
# Relay watcher (VERDICT r4 Weak #5): poll the axon relay port and fire
# the serialized measurement queue the moment it answers, so a short
# relay window is never missed between builder turns.
#
# Usage: bash tools/relay_watch.sh [logfile]   (run it in background)
# Exits after ONE queue run; re-launch to watch for another window.
set -u
cd "$(dirname "$0")/.."
LOG=${1:-/tmp/relay_watch.log}
PORT=${AXON_RELAY_PORT:-8082}
# WATCH_DEADLINE_EPOCH (optional): stop watching past this time and
# export it to the queue as its hard deadline, so a late relay window
# never leaves the flock held into the driver's own bench run
DEADLINE=${WATCH_DEADLINE_EPOCH:-}
{
  echo "[relay_watch] start $(date -u +%FT%TZ) port=$PORT deadline=${DEADLINE:-none}"
  while :; do
    # checked here (not only in the wait loop) so the rc!=0 retry path
    # can never fire the queue past the deadline either
    if [ -n "$DEADLINE" ] && [ "$(date +%s)" -ge "$DEADLINE" ]; then
      echo "[relay_watch] deadline passed; exiting"
      exit 0
    fi
    until timeout 3 bash -c "echo > /dev/tcp/127.0.0.1/$PORT" 2>/dev/null; do
      if [ -n "$DEADLINE" ] && [ "$(date +%s)" -ge "$DEADLINE" ]; then
        echo "[relay_watch] deadline passed while waiting; exiting"
        exit 0
      fi
      sleep "${RELAY_WATCH_INTERVAL:-120}"
    done
    echo "[relay_watch] relay UP $(date -u +%FT%TZ) — firing tpu_queue"
    QUEUE_HARD_DEADLINE_EPOCH="$DEADLINE" bash tools/tpu_queue.sh /tmp/tpu_queue.log
    rc=$?
    echo "[relay_watch] queue done rc=$rc $(date -u +%FT%TZ)"
    # rc=1 (flock held by a manual run) or rc=2 (relay died between the
    # probe and the queue's own probe): the window is NOT consumed —
    # re-enter the wait loop instead of abandoning the watch
    [ "$rc" -eq 0 ] && break
    sleep "${RELAY_WATCH_INTERVAL:-120}"
  done
} >>"$LOG" 2>&1
