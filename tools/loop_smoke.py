#!/usr/bin/env python
"""Closed-loop smoke: a real ``task=serve_train`` process, end to end.

Drives the full production loop the way an operator would (ISSUE 6
acceptance): launch ``python -m cxxnet_tpu <conf> task=serve_train``
against a freshly trained checkpoint, POST >= 1k feedback records over
HTTP in two phases — first deliberately POISONED labels (the eval gate
must reject the degraded candidate and the trainer must roll back),
then correct labels (the gate must publish and the engine must
hot-reload the new weights fingerprint) — and verify every claim from
the outside: the event log for ``loop.reject`` / ``loop.rollback`` /
``loop.publish``, ``/healthz`` for the served round + crc, ``/metricsz``
for the gauges.  Emits one JSON verdict line on stdout::

    {"ok": true, "records": 1256, "rejected": ..., "published": ...,
     "round_before": 1, "round_after": 2, "crc_changed": true, ...}

Wired into tier-1 as the opt-in ``LOOP=1`` lane (tools/run_tier1.sh).

Usage: python tools/loop_smoke.py [--out DIR] [--records N]
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

CONF = """
data = train
iter = synthetic
  nsample = 256
  input_shape = 1,1,16
  nclass = 4
  seed_data = 1
iter = end
eval = heldout
iter = synthetic
  nsample = 256
  input_shape = 1,1,16
  nclass = 4
  seed_data = 1
iter = end

netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 16
  init_sigma = 0.1
layer[+1:a1] = relu:a1
layer[a1->out] = fullc:fc2
  nhidden = 4
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end

input_shape = 1,1,16
batch_size = 32
dev = cpu
eta = 0.05
metric = error
"""


def _post(port: int, path: str, obj: dict, timeout: float = 30.0) -> dict:
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(obj).encode("utf-8"),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _get(port: int, path: str, timeout: float = 10.0):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=timeout
    ) as r:
        body = r.read()
    return json.loads(body) if path != "/metricsz" else body.decode()


def _events(path: str, kind: str):
    out = []
    try:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                try:
                    e = json.loads(line)
                except ValueError:
                    continue
                if e.get("kind") == kind:
                    out.append(e)
    except OSError:
        pass
    return out


def _wait_for(predicate, what: str, timeout_s: float = 120.0,
              poll_s: float = 0.5):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        got = predicate()
        if got:
            return got
        time.sleep(poll_s)
    raise TimeoutError(f"timed out after {timeout_s:.0f}s waiting for {what}")


def _fail(msg: str, proc=None) -> None:
    if proc is not None:
        proc.kill()
        out = proc.stdout.read() if proc.stdout else ""
        sys.stderr.write(f"--- serve_train output ---\n{out}\n")
    print(json.dumps({"ok": False, "error": msg}), flush=True)
    raise SystemExit(1)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None,
                    help="workdir (default: a fresh temp dir)")
    ap.add_argument("--records", type=int, default=1200,
                    help="total feedback records to ingest (>= 1000)")
    args = ap.parse_args()
    t_start = time.monotonic()
    work = args.out or tempfile.mkdtemp(prefix="loop_smoke_")
    os.makedirs(work, exist_ok=True)
    conf_path = os.path.join(work, "loop.conf")
    with open(conf_path, "w", encoding="utf-8") as f:
        f.write(CONF)
    mdir = os.path.join(work, "models")
    events_path = os.path.join(work, "events.jsonl")

    # ---- the initial serving checkpoint (one quick training round)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import numpy as np

    from cxxnet_tpu import config as cfgmod
    from cxxnet_tpu.io.data import create_iterator
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.utils import checkpoint as ckpt

    cfg = cfgmod.parse_pairs(CONF)
    split = cfgmod.split_sections(cfg)
    tr = NetTrainer()
    tr.set_params(split.global_entries)
    tr.set_param("seed", "0")
    tr.init_model()
    it = create_iterator(split.sections[0].entries)
    it.set_param("batch_size", "32")
    it.init()
    rows, labs = [], []
    while it.next():
        b = it.value()
        rows.append(np.asarray(b.data).copy())
        labs.append(np.asarray(b.label).copy())
        tr.update_all(b.data, b.label)
    X, Y = np.concatenate(rows), np.concatenate(labs)
    os.makedirs(mdir, exist_ok=True)
    ckpt.write_checkpoint(
        ckpt.publish_path(mdir, 1), tr.checkpoint_bytes(), round_=1,
        net_fp=tr.net_fp(),
    )

    # ---- launch the serve_train process
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO  # keep test-style axon-free jax
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, "-m", "cxxnet_tpu", conf_path,
         "task=serve_train", f"model_dir={mdir}",
         f"loop_dir={os.path.join(work, 'loop')}",
         "serve_port=0", "loop_cycle_period_s=0.5",
         "loop_min_records=200", "loop_rounds_per_cycle=2",
         "loop_replay_ratio=0.25",
         f"event_log={events_path}", "silent=0"],
        env=env, cwd=work, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )
    port = None
    try:
        # the CLI prints the bound port on the ready line
        t0 = time.monotonic()
        for line in proc.stdout:
            sys.stderr.write(line)
            if "http://" in line:
                port = int(line.rsplit(":", 1)[1].split(";")[0]
                           .split("/")[0].strip())
                break
            if time.monotonic() - t0 > 180 or proc.poll() is not None:
                break
        if port is None:
            _fail("serve_train never reported a ready port", proc)
        # keep draining the child's stdout (verbose request logging
        # would fill the pipe and wedge the server otherwise)
        import threading

        threading.Thread(
            target=lambda: [None for _ in proc.stdout], daemon=True
        ).start()
        h0 = _get(port, "/healthz")
        round_before, crc_before = h0["round"], h0["model_crc32"]

        n_poison = args.records // 2
        n_correct = args.records - n_poison
        ingested = 0

        def post_rows(data, labels, chunk=32):
            nonlocal ingested
            for lo in range(0, data.shape[0], chunk):
                out = _post(port, "/feedback", {
                    "data": data[lo: lo + chunk].tolist(),
                    "label": labels[lo: lo + chunk].tolist(),
                })
                ingested += out["appended"]

        # ---- phase A: poisoned labels -> gate must reject + roll back
        idx = np.arange(n_poison) % X.shape[0]
        post_rows(X[idx], ((Y[idx] + 1.0) % 4))
        _wait_for(lambda: _events(events_path, "loop.reject"),
                  "the eval gate to reject the poisoned candidate")
        _wait_for(lambda: _events(events_path, "loop.rollback"),
                  "the trainer rollback event")
        h1 = _get(port, "/healthz")
        if h1["round"] != round_before:
            _fail(f"degraded candidate was served: round {h1['round']}",
                  proc)
        # every poisoned record consumed before the correct phase (the
        # publish must provably come from clean data)
        _wait_for(
            lambda: sum(c.get("records", 0)
                        for c in _events(events_path, "loop.cycle"))
            >= n_poison,
            "all poisoned records to be consumed")

        # ---- phase B: correct labels -> gate must publish + hot-reload
        idx = np.arange(n_correct) % X.shape[0]
        post_rows(X[idx], Y[idx])
        publishes = _wait_for(
            lambda: _events(events_path, "loop.publish"),
            "the eval gate to publish the improving candidate")
        _wait_for(lambda: _get(port, "/healthz")["round"] > round_before,
                  "the engine to hot-reload the published round")
        # loop.cycle is emitted after loop.publish: let the trained
        # cycles' own records land before the verdict counts them
        _wait_for(lambda: len(_events(events_path, "loop.cycle")) >= 2,
                  "both trained cycles' records")
        h2 = _get(port, "/healthz")
        mez = _get(port, "/metricsz")
        for needle in (f"serve_model_round {h2['round']}",
                       "loop_feedback_records_total",
                       'loop_publish_total{decision="published"}',
                       'loop_publish_total{decision="rejected"}'):
            if needle not in mez:
                _fail(f"/metricsz is missing {needle!r}", proc)

        # lineage: the publish pointer must name the id range that
        # trained the served model, and obs_dump --lineage must resolve
        # it back to committed feedback pages (ISSUE 7 acceptance)
        import obs_dump

        lineage_report, lineage_problems = obs_dump.resolve_lineage(
            mdir, os.path.join(work, "loop", "feedback"))
        lin = lineage_report.get("lineage") or {}
        resolved = lineage_report.get("resolved") or {}
        lineage_ok = (not lineage_problems
                      and isinstance(lin.get("first_seq"), int)
                      and isinstance(lin.get("last_seq"), int)
                      and lin.get("records", 0) >= 1
                      and resolved.get("records_in_range", 0) >= 1)

        verdict = {
            "ok": True,
            "records": ingested,
            "lineage": lin or None,
            "lineage_resolved": lineage_ok,
            "rejected": len(_events(events_path, "loop.reject")),
            "rollbacks": len(_events(events_path, "loop.rollback")),
            "published": len(publishes),
            "cycles": len(_events(events_path, "loop.cycle")),
            "round_before": round_before,
            "round_after": h2["round"],
            "crc_changed": h2["model_crc32"] != crc_before,
            "gain": publishes[-1].get("gain"),
            "elapsed_s": round(time.monotonic() - t_start, 1),
        }
        ok = (verdict["records"] >= 1000 and verdict["rejected"] >= 1
              and verdict["rollbacks"] >= 1 and verdict["published"] >= 1
              and verdict["cycles"] >= 2
              and verdict["round_after"] > verdict["round_before"]
              and verdict["crc_changed"] and verdict["lineage_resolved"])
        verdict["ok"] = bool(ok)
        # ---- graceful drain
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=60)
        verdict["exit_code"] = rc
        verdict["ok"] = verdict["ok"] and rc == 0
        print(json.dumps(verdict), flush=True)
        raise SystemExit(0 if verdict["ok"] else 1)
    finally:
        if proc.poll() is None:
            proc.kill()


if __name__ == "__main__":
    main()
