"""bf16 Winograd + branch-embed A/B (CPU, relay-independent).

The F(4x4,3x3) tile's transform constants reach |8|, amplifying bf16
rounding ~15x vs the direct conv (``cxxnet_tpu/layers/conv.py`` — the
known fp16-Winograd tradeoff); F(2x2,3x3) stays within ~3x.  Layer-level
pair tests bound the per-op error; this tool characterizes what that
error does to END-TO-END TRAINING — the evidence a default flip needs
(the reference's pairtest ethos applied at model scale,
``/root/reference/src/layer/pairtest_layer-inl.hpp:160-198``).

Two model-scale probes, all under ``compute_dtype = bfloat16``:

* digits-conv (``example/MNIST/digits_conv.conf``, real handwritten
  digits, the repo's MNIST stand-in): full 15-round test-error
  trajectory for conv_wino = 0 / 1 / 2 (+ an fp32 direct reference);
* GoogLeNet membuffer-overfit (the ``iter = membuffer`` one-batch
  discipline): steps until eval error hits 0 — a deep-net gradient-path
  sanity check with 3x3 branches on the Winograd path.

A third probe (``--bembed-only``) records the CPU half of the
branch-embedding promotion verdict (PR 10 flipped
``conv_branch_embed`` to auto: ON for inference program builds): on
the GoogLeNet builder conf it measures fused-vs-unfused EXACTNESS of
the inference forward (max |score delta| + top-1 flips over random
batches) and the CPU predict throughput delta.  PROMOTE requires
zero top-1 flips and throughput inside a 10% band; the on-chip
step-time A/B for the train side stays queued in ``tpu_queue.sh``
(``googlenet_bisect.py bembed``).

Usage:  python tools/wino_bf16_ab.py
        [--digits-only|--googlenet-only|--bembed-only]
Writes: example/MNIST/wino_bf16_ab.log (the committed artifact).
"""

import os
import re
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

LOG_PATH = os.path.join(REPO, "example", "MNIST", "wino_bf16_ab.log")


def _cpu_env() -> dict:
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO  # drop .axon_site -> never dials the relay
    env["JAX_PLATFORMS"] = "cpu"
    return env


def digits_trajectory(workdir: str, extra_args) -> dict:
    """Run the digits-conv recipe through the real CLI; return
    {round: test_error}."""
    r = subprocess.run(
        [sys.executable, "-m", "cxxnet_tpu", "digits_conv.conf",
         "task=train", "save_model=0"] + list(extra_args),
        cwd=workdir, env=_cpu_env(), capture_output=True, text=True,
    )
    if r.returncode != 0:
        raise RuntimeError(f"digits run failed: {r.stderr[-2000:]}")
    return {
        int(m.group(1)): float(m.group(2))
        for m in re.finditer(
            r"\[(\d+)\]\ttrain-error:\S+\ttest-error:(\S+)", r.stderr)
    }


def run_digits(out) -> None:
    import shutil

    tmp = tempfile.mkdtemp(prefix="wino_ab_")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "make_digits_idx.py"),
         os.path.join(tmp, "data")],
        capture_output=True, text=True,
    )
    if r.returncode != 0:
        raise RuntimeError(f"make_digits_idx failed: {r.stderr}")
    shutil.copy(os.path.join(REPO, "example", "MNIST", "digits_conv.conf"),
                os.path.join(tmp, "digits_conv.conf"))
    variants = [
        ("fp32 direct", []),
        ("bf16 direct", ["compute_dtype=bfloat16"]),
        ("bf16 wino F(4x4)", ["compute_dtype=bfloat16", "conv_wino=1"]),
        ("bf16 wino F(2x2)", ["compute_dtype=bfloat16", "conv_wino=2"]),
    ]
    results = {}
    for name, args in variants:
        t0 = time.time()
        errs = digits_trajectory(tmp, args)
        results[name] = errs
        out(f"# digits {name}: {time.time() - t0:.0f}s, "
            f"round-15 test-error {errs.get(15, float('nan')):.4f}")
    out("")
    out("digits-conv, 15 rounds, test-error trajectory")
    out("round | " + " | ".join(n for n, _ in variants))
    rounds = sorted(results[variants[0][0]])
    for k in rounds:
        out(f"{k:5d} | " + " | ".join(
            f"{results[n].get(k, float('nan')):11.4f}" for n, _ in variants))
    out("")
    shutil.rmtree(tmp, ignore_errors=True)


def googlenet_overfit(wino: int, n_steps: int = 300):
    """Return (steps_to_zero_err, final_err) for a bf16 GoogLeNet
    membuffer overfit with the given conv_wino."""
    from cxxnet_tpu import config as C
    from cxxnet_tpu.io.data import create_iterator
    from cxxnet_tpu.models import googlenet_conf
    from cxxnet_tpu.nnet.trainer import NetTrainer

    it = create_iterator(C.split_sections(C.parse_pairs("""
data = train
iter = synthetic
  nsample = 8
  input_shape = 3,64,64
  nclass = 10
  label_width = 1
  batch_size = 8
iter = membuffer
  max_nbatch = 1
iter = end
""")).find("data")[0].entries)
    it.init()
    tr = NetTrainer()
    tr.set_params(C.parse_pairs(googlenet_conf(
        batch_size=8, num_class=10, synthetic=False, dev="cpu",
        input_size=64)))
    for k, v in [("updater", "adam"), ("eta", "0.001"),
                 ("wmat:lr", "0.001"), ("bias:lr", "0.001"),
                 ("wd", "0.0"), ("wmat:wd", "0.0"),
                 ("compute_dtype", "bfloat16"),
                 ("conv_wino", str(wino))]:
        tr.set_param(k, v)
    tr.eval_train = 0
    tr.init_model()
    it.before_first()
    assert it.next()
    cached = it.value()
    err = 1.0
    for step in range(n_steps):
        it.before_first()
        while it.next():
            tr.update(it.value())
        if (step + 1) % 25 == 0:
            pred = tr.predict(cached)
            err = float((pred != cached.label[:, 0]).mean())
            if err == 0.0:
                return step + 1, err
    return None, err


def run_googlenet(out) -> None:
    out("GoogLeNet bf16 membuffer-overfit (8 cached images, adam 1e-3;"
        " steps checked every 25)")
    out("conv_wino | steps-to-0-error | final-error")
    for wino in (0, 1, 2):
        t0 = time.time()
        steps, err = googlenet_overfit(wino)
        out(f"{wino:9d} | {steps if steps is not None else '>300':>16} "
            f"| {err:.3f}   ({time.time() - t0:.0f}s)")
    out("")


def run_bembed(out) -> None:
    """CPU promote/reject evidence for inference-build branch-embed:
    exactness (top-1 flips must be 0) + predict-throughput band."""
    import numpy as np

    from cxxnet_tpu import config as C
    from cxxnet_tpu.io.data import DataBatch
    from cxxnet_tpu.models import googlenet_conf
    from cxxnet_tpu.nnet.trainer import NetTrainer

    def build(bembed: str):
        tr = NetTrainer()
        tr.set_params(C.parse_pairs(googlenet_conf(
            batch_size=8, num_class=10, synthetic=False, dev="cpu",
            input_size=64)))
        tr.set_param("conv_branch_embed", bembed)
        tr.set_param("seed", "7")
        tr.init_model()
        return tr

    t_off, t_on = build("0"), build("1")
    rng = np.random.RandomState(0)
    flips = 0
    max_dd = 0.0
    rates = {}
    for name, tr in (("unfused", t_off), ("fused", t_on)):
        b = DataBatch(data=rng.rand(8, 64, 64, 3).astype(np.float32),
                      label=np.zeros((8, 1), np.float32))
        tr.predict(b)  # warm the compile
    for k in range(6):
        x = rng.rand(8, 64, 64, 3).astype(np.float32)
        b = DataBatch(data=x, label=np.zeros((8, 1), np.float32))
        s_off = t_off.extract_feature(b, "top[-1]")
        s_on = t_on.extract_feature(b, "top[-1]")
        max_dd = max(max_dd, float(np.abs(s_off - s_on).max()))
        flips += int((s_off.argmax(1) != s_on.argmax(1)).sum())
    for name, tr in (("unfused", t_off), ("fused", t_on)):
        b = DataBatch(data=rng.rand(8, 64, 64, 3).astype(np.float32),
                      label=np.zeros((8, 1), np.float32))
        t0 = time.time()
        n = 0
        while time.time() - t0 < 5.0:
            tr.predict(b)
            n += 8
        rates[name] = n / (time.time() - t0)
    ratio = rates["fused"] / rates["unfused"]
    verdict = ("PROMOTE" if flips == 0 and ratio >= 0.9 else "REJECT")
    out("branch-embed inference A/B (GoogLeNet 64px b8, CPU)")
    out(f"  top-1 flips over 48 rows: {flips}; max |score delta| "
        f"{max_dd:.2e}")
    out(f"  predict rows/s unfused {rates['unfused']:.1f} -> fused "
        f"{rates['fused']:.1f} (ratio {ratio:.3f})")
    out(f"  CPU-backend verdict: {verdict} (exactness + 10% band) — "
        "the conv_branch_embed=-1 auto default follows it: fused "
        "inference builds on accelerator backends only, never on "
        "CPU; the on-chip confirmation stays queued "
        "(googlenet_bisect.py bembed / serve_bench --quant)")
    out("")


def main() -> None:
    lines = []

    def out(s: str) -> None:
        print(s, flush=True)
        lines.append(s)

    only = [a for a in sys.argv[1:] if a.endswith("-only")]
    out(f"# wino_bf16_ab @ {time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime())}")
    if not only or "--digits-only" in only:
        run_digits(out)
    if not only or "--googlenet-only" in only:
        run_googlenet(out)
    if not only or "--bembed-only" in only:
        run_bembed(out)
    # append: split --digits-only / --googlenet-only invocations build
    # one log; the timestamp header delimits runs
    with open(LOG_PATH, "a") as f:
        f.write("\n".join(lines) + "\n")
    print(f"# wrote {LOG_PATH}")


if __name__ == "__main__":
    main()
