"""Serving latency/throughput benchmark (closed-loop + open-loop).

Drives the serving engine (``cxxnet_tpu/serve``) in-process over a
synthetic MLP — no HTTP in the way, so the numbers isolate the
micro-batcher + compiled-predict-cache data path:

* **closed-loop**: C worker threads, each firing its next request the
  moment the previous one returns — measures saturated throughput and
  the batching speedup over a single sequential client (the ISSUE-2
  acceptance bar: >= 3x at concurrency 16);
* **open-loop**: requests arrive on a fixed-rate clock regardless of
  completions (the honest way to measure latency under load — a
  closed loop self-throttles and hides queueing collapse); reports
  achieved rate and p50/p95/p99 latency at each offered rate.
* **open-loop burst profile** (``--open-loop --burst``): a square-wave
  arrival schedule alternating ``--base-rate`` and ``--burst-rate``
  every ``--phase`` seconds, sustained for ``--duration`` seconds (or
  until ``--total-requests`` arrivals — the ROADMAP's >= 10^6-request
  story; the full-scale invocation is queued in ``tpu_queue.sh``, a
  scaled-down one runs in the FLEET=1 tier-1 lane).  Reports sustained
  p50/p99 plus explicit shed (429) / expired (504) / error counts, so
  admission-control behavior under burst pressure is a first-class
  series.  ``--url`` points the same harness at a running HTTP front
  end (e.g. the serving fleet) instead of the in-process engine; the
  URL client keeps one keep-alive connection per worker thread, and
  ``--wire binary`` posts CXB1 frames (doc/serving.md "Binary wire
  protocol") instead of JSON.
* **wire A/B** (``--wire-ab``): JSON-vs-binary closed-loop throughput
  over real HTTP against an in-process server — interleaved best-of-2
  legs plus a bitwise score-parity check (the WIRE=1 lane's >= 1.5x
  acceptance bar and the ``wire_bench`` perf-guard series).

Prints one JSON document on stdout.

Usage::

    python tools/serve_bench.py [--model mnist_mlp] [--dev cpu]
        [--concurrency 16] [--requests 200] [--rows 1]
        [--max-batch 64] [--timeout-ms 2] [--open-rates 100,500]
        [--open-duration 3]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_engine(args, scheme: str = ""):
    """Engine + request tensor over the builder conf.  ``scheme``
    quantizes the trainer's kernels in place (per-channel int8 +
    folded rescale, ``nnet/quant.py``) before the engine wraps it —
    the quant leg of the A/B serves the SAME conf and seed at reduced
    precision, through the identical construction path."""
    from cxxnet_tpu import config as cfgmod
    from cxxnet_tpu import serve
    from cxxnet_tpu.models import MODEL_BUILDERS
    from cxxnet_tpu.nnet.trainer import NetTrainer

    conf = MODEL_BUILDERS[args.model](
        batch_size=args.max_batch, dev=args.dev
    )
    tr = NetTrainer()
    tr.set_params(cfgmod.parse_pairs(conf))
    tr.init_model()
    if scheme:
        from cxxnet_tpu.nnet import quant as nquant

        nquant.apply_plan(tr, nquant.build_plan(tr, scheme), scheme)
    eng = serve.Engine(
        trainer=tr,
        max_batch_size=args.max_batch,
        batch_timeout_ms=args.timeout_ms,
        queue_limit=max(1024, 4 * args.concurrency),
    )
    row = tuple(tr.net.input_node_shape(1)[1:])
    x = np.random.RandomState(0).rand(args.rows, *row).astype(np.float32)
    return eng, x


def closed_loop(eng, x, concurrency, requests):
    """Each of ``concurrency`` threads runs ``requests`` back-to-back."""
    lat = []
    lock = threading.Lock()

    def worker():
        mine = []
        for _ in range(requests):
            t0 = time.perf_counter()
            eng.predict(x)
            mine.append(time.perf_counter() - t0)
        with lock:
            lat.extend(mine)

    threads = [threading.Thread(target=worker) for _ in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    lat.sort()
    n = len(lat)
    return {
        "concurrency": concurrency,
        "requests": n,
        "wall_sec": wall,
        "req_per_sec": n / wall,
        "rows_per_sec": n * x.shape[0] / wall,
        "latency_ms": {
            "p50": lat[n // 2] * 1e3,
            "p95": lat[min(n - 1, int(n * 0.95))] * 1e3,
            "p99": lat[min(n - 1, int(n * 0.99))] * 1e3,
        },
    }


def open_loop(eng, x, rate, duration):
    """Fixed-rate arrivals for ``duration`` seconds; late completions
    still count — achieved < offered means the server cannot keep up."""
    from cxxnet_tpu import serve as _serve

    lat, errs = [], [0]
    lock = threading.Lock()
    threads = []

    def fire():
        t0 = time.perf_counter()
        try:
            eng.predict(x)
        except _serve.ServeError:
            with lock:
                errs[0] += 1
            return
        dt = time.perf_counter() - t0
        with lock:
            lat.append(dt)

    period = 1.0 / rate
    t_start = time.perf_counter()
    k = 0
    while True:
        t_next = t_start + k * period
        now = time.perf_counter()
        if now - t_start >= duration:
            break
        if t_next > now:
            time.sleep(t_next - now)
        th = threading.Thread(target=fire)
        th.start()
        threads.append(th)
        k += 1
    for th in threads:
        th.join()
    wall = time.perf_counter() - t_start
    lat.sort()
    n = len(lat)
    out = {
        "offered_req_per_sec": rate,
        "sent": k,
        "completed": n,
        "shed_or_error": errs[0],
        "achieved_req_per_sec": n / wall,
    }
    if n:
        out["latency_ms"] = {
            "p50": lat[n // 2] * 1e3,
            "p95": lat[min(n - 1, int(n * 0.95))] * 1e3,
            "p99": lat[min(n - 1, int(n * 0.99))] * 1e3,
        }
    return out


def open_loop_burst(fire, base_rate, burst_rate, phase_s, duration_s,
                    total_requests=0, clients=64, progress_s=0.0):
    """Square-wave open-loop driver: arrivals alternate between
    ``base_rate`` and ``burst_rate`` req/s every ``phase_s`` seconds.

    ``fire()`` executes one request and returns ``(outcome, dt)`` with
    outcome one of ``ok`` / ``shed`` (429) / ``expired`` (504) /
    ``error``.  A fixed pool of ``clients`` workers drains a bounded
    arrival queue, so arrivals are never blocked by completions; if the
    pool cannot keep up the queue overflows into ``client_drop``
    (reported — a silent cap would read as 'covered the offered load'
    when it didn't).  ``progress_s > 0`` streams running counts and
    p50/p99 to stderr every that-many seconds — the >= 10^6-request
    story's live telemetry."""
    import queue as _q

    lat = []
    counts = {"ok": 0, "shed": 0, "expired": 0, "error": 0,
              "client_drop": 0}
    lock = threading.Lock()
    work: "_q.Queue" = _q.Queue(maxsize=10000)

    def worker():
        while True:
            item = work.get()
            if item is None:
                return
            outcome, dt = fire()
            with lock:
                counts[outcome] = counts.get(outcome, 0) + 1
                if outcome == "ok":
                    lat.append(dt)

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(clients)]
    for t in threads:
        t.start()
    t0 = time.perf_counter()
    t_next = t0
    t_report = t0 + progress_s
    sent = 0
    while True:
        now = time.perf_counter()
        elapsed = now - t0
        if progress_s > 0 and now >= t_report:
            t_report = now + progress_s
            with lock:
                snap = sorted(lat)
                done = dict(counts)
            n = len(snap)
            p50 = snap[n // 2] * 1e3 if n else float("nan")
            p99 = snap[min(n - 1, int(n * 0.99))] * 1e3 if n \
                else float("nan")
            print(f"burst[{elapsed:.0f}s] sent {sent} ok {n} "
                  f"shed {done['shed']} expired {done['expired']} "
                  f"err {done['error']} p50 {p50:.2f} ms "
                  f"p99 {p99:.2f} ms",
                  file=sys.stderr, flush=True)
        if total_requests and sent >= total_requests:
            break
        if not total_requests and elapsed >= duration_s:
            break
        if t_next > now:
            time.sleep(min(t_next - now, 0.01))
            continue
        try:
            work.put_nowait(1)
            sent += 1
        except _q.Full:
            with lock:
                counts["client_drop"] += 1
        in_burst = int(elapsed / phase_s) % 2 == 1
        rate = burst_rate if in_burst else base_rate
        t_next += 1.0 / max(rate, 1e-9)
        if t_next < now - 1.0:
            t_next = now  # don't unwind a deep arrival backlog forever
    for _ in threads:
        work.put(None)
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    lat.sort()
    n = len(lat)
    out = {
        "base_rate": base_rate,
        "burst_rate": burst_rate,
        "phase_s": phase_s,
        "wall_sec": wall,
        "sent": sent,
        "completed": n,
        "shed": counts["shed"],
        "expired": counts["expired"],
        "errors": counts["error"],
        "client_drop": counts["client_drop"],
        "achieved_req_per_sec": n / wall if wall > 0 else 0.0,
    }
    if n:
        out["latency_ms"] = {
            "p50": lat[n // 2] * 1e3,
            "p95": lat[min(n - 1, int(n * 0.95))] * 1e3,
            "p99": lat[min(n - 1, int(n * 0.99))] * 1e3,
        }
    return out


def make_engine_fire(eng, x, deadline_ms=0.0):
    """Burst-driver fire() over the in-process engine."""
    from cxxnet_tpu import serve as _serve

    def fire():
        t0 = time.perf_counter()
        try:
            eng.predict(x, deadline_ms=deadline_ms or None)
        except _serve.ServeError as e:
            kind = ("shed" if e.http_status == 429
                    else "expired" if e.http_status == 504 else "error")
            return kind, time.perf_counter() - t0
        except Exception:  # noqa: BLE001 - counted, bench keeps going
            return "error", time.perf_counter() - t0
        return "ok", time.perf_counter() - t0

    return fire


def make_url_fire(url, x, deadline_ms=0.0, priority="", wire_fmt="json"):
    """fire() over a running HTTP front end (single engine or fleet
    router) — POST /predict per request on a **per-thread pooled
    keep-alive connection** (``http.client``), not a fresh socket per
    request: the old ``urlopen``-per-request client spent most of its
    budget on TCP setup and measured the connect path, not the server.

    ``wire_fmt="binary"`` posts one pre-encoded CXB1 frame per request
    (doc/serving.md "Binary wire protocol") instead of JSON.  A stale
    pooled connection (server restarted, idle timeout) gets one
    fresh-socket retry; /predict is idempotent."""
    import http.client
    import urllib.parse

    u = urllib.parse.urlsplit(url if "//" in url else "http://" + url)
    host = u.hostname or "127.0.0.1"
    port = u.port or 80
    path = u.path.rstrip("/") + "/predict"
    if wire_fmt == "binary":
        from cxxnet_tpu.serve import wire as _wire

        payload = bytes(_wire.encode_request(
            x, kind="predict", priority=priority or "interactive",
            deadline_ms=deadline_ms))
        ctype = _wire.CONTENT_TYPE
    else:
        body = {"data": x.tolist()}
        if deadline_ms:
            body["deadline_ms"] = deadline_ms
        if priority:
            body["priority"] = priority
        payload = json.dumps(body).encode("utf-8")
        ctype = "application/json"
    tls = threading.local()

    def fire():
        t0 = time.perf_counter()
        status = None
        for attempt in (0, 1):
            conn = getattr(tls, "conn", None)
            fresh = conn is None
            if fresh:
                conn = http.client.HTTPConnection(host, port, timeout=30)
                tls.conn = conn
            try:
                conn.request("POST", path, body=payload,
                             headers={"Content-Type": ctype})
                r = conn.getresponse()
                r.read()
                status = r.status
                if r.will_close:
                    conn.close()
                    tls.conn = None
                break
            except (http.client.HTTPException, OSError):
                conn.close()
                tls.conn = None
                if fresh or attempt:
                    return "error", time.perf_counter() - t0
        dt = time.perf_counter() - t0
        if status == 200:
            return "ok", dt
        if status == 429:
            return "shed", dt
        if status == 504:
            return "expired", dt
        return "error", dt

    return fire


def closed_loop_http(fire, concurrency, requests, rows):
    """Closed loop over a pooled HTTP fire(): each worker reuses ONE
    keep-alive connection for all its requests."""
    lat = []
    errs = [0]
    lock = threading.Lock()

    def worker():
        mine = []
        for _ in range(requests):
            outcome, dt = fire()
            if outcome == "ok":
                mine.append(dt)
            else:
                with lock:
                    errs[0] += 1
        with lock:
            lat.extend(mine)

    threads = [threading.Thread(target=worker) for _ in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    lat.sort()
    n = len(lat)
    out = {
        "concurrency": concurrency,
        "requests": n,
        "errors": errs[0],
        "wall_sec": wall,
        "req_per_sec": n / wall if wall > 0 else 0.0,
        "rows_per_sec": n * rows / wall if wall > 0 else 0.0,
    }
    if n:
        out["latency_ms"] = {
            "p50": lat[n // 2] * 1e3,
            "p95": lat[min(n - 1, int(n * 0.95))] * 1e3,
            "p99": lat[min(n - 1, int(n * 0.99))] * 1e3,
        }
    return out


def check_wire_parity(url, x):
    """One row batch through both planes: binary scores must be
    BITWISE equal to the JSON scores (tolist() of f32 round-trips
    through float64 repr exactly)."""
    import urllib.request

    from cxxnet_tpu.serve import wire as _wire

    base = url.rstrip("/")
    req = urllib.request.Request(
        base + "/predict",
        data=json.dumps({"data": x.tolist(), "raw": True}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as r:
        jscores = np.asarray(json.loads(r.read())["scores"], np.float32)
    req = urllib.request.Request(
        base + "/predict",
        data=bytes(_wire.encode_request(x, kind="scores")),
        headers={"Content-Type": _wire.CONTENT_TYPE})
    with urllib.request.urlopen(req, timeout=30) as r:
        _k, _rid, wscores = _wire.decode_response(r.read())
    return bool(np.asarray(wscores, np.float32).tobytes()
                == jscores.tobytes())


def run_wire_ab(args) -> dict:
    """JSON-vs-binary wire A/B over real HTTP (the WIRE=1 lane's
    measurement and the ``wire_bench`` perf-guard series): the engine
    behind its own stdlib server, pooled keep-alive clients on both
    formats, interleaved best-of-2 closed-loop legs — back to back, so
    machine-load drift hits both equally (the autotune discipline) —
    plus the bitwise score-parity bit."""
    from cxxnet_tpu import serve

    eng, x = build_engine(args)
    httpd = serve.make_server(eng, port=0)
    th = threading.Thread(target=httpd.serve_forever, daemon=True)
    th.start()
    url = f"http://127.0.0.1:{httpd.server_port}"
    fire_j = make_url_fire(url, x, wire_fmt="json")
    fire_b = make_url_fire(url, x, wire_fmt="binary")
    try:
        for _ in range(8):
            fire_j()
            fire_b()
        parity = check_wire_parity(url, x)
        half = max(8, args.requests // 2)
        j_runs, b_runs = [], []
        for _ in range(2):
            b_runs.append(closed_loop_http(
                fire_b, args.concurrency, half, x.shape[0]))
            j_runs.append(closed_loop_http(
                fire_j, args.concurrency, half, x.shape[0]))
        jbest = max(j_runs, key=lambda r: r["req_per_sec"])
        bbest = max(b_runs, key=lambda r: r["req_per_sec"])
    finally:
        httpd.shutdown()
        httpd.server_close()
        eng.close()
    return {
        "model": args.model,
        "dev": args.dev,
        "rows_per_request": args.rows,
        "max_batch_size": args.max_batch,
        "wire_ab": {
            "json": jbest,
            "binary": bbest,
            "speedup": (bbest["req_per_sec"] / jbest["req_per_sec"]
                        if jbest["req_per_sec"] > 0 else 0.0),
            "bitwise_equal_scores": parity,
        },
    }


def run_open_loop_burst(args) -> dict:
    """The ``--open-loop --burst`` entry: in-process engine by default,
    a running front end with ``--url``."""
    eng = None
    if args.url:
        row = [0.5] * 16
        x = np.asarray([row] * args.rows, np.float32)
        fire = make_url_fire(args.url, x, deadline_ms=args.deadline_ms,
                             wire_fmt=args.wire)
    else:
        eng, x = build_engine(args)
        for _ in range(8):
            eng.predict(x)
        fire = make_engine_fire(eng, x, deadline_ms=args.deadline_ms)
    burst = open_loop_burst(
        fire, args.base_rate, args.burst_rate, args.phase,
        args.open_duration, total_requests=args.total_requests,
        clients=args.clients, progress_s=args.progress_s)
    result = {
        "model": args.model,
        "dev": args.dev,
        "url": args.url or None,
        "rows_per_request": args.rows,
        "max_batch_size": args.max_batch,
        "open_loop_burst": burst,
    }
    if eng is not None:
        result["serving_stats"] = eng.snapshot_stats()
        eng.close()
    return result


def run_quant_ab(args) -> dict:
    """f32-vs-quantized serving A/B (the QUANT lane's measurement and
    the TPU-queue entry): interleaved closed-loop legs — best-of-2 per
    side, back to back, so machine-load drift hits both equally (the
    autotune discipline) — plus the weight-bytes identity both engines
    report."""
    from cxxnet_tpu.ops import quant as opsq

    eng_f, x = build_engine(args)
    eng_q, _ = build_engine(args, scheme=args.quant)
    for _ in range(8):
        eng_f.predict(x)
        eng_q.predict(x)
    half = max(8, args.requests // 2)
    f_runs, q_runs = [], []
    for _ in range(2):
        q_runs.append(closed_loop(eng_q, x, args.concurrency, half))
        f_runs.append(closed_loop(eng_f, x, args.concurrency, half))
    f32 = max(f_runs, key=lambda r: r["req_per_sec"])
    qnt = max(q_runs, key=lambda r: r["req_per_sec"])
    wb_f, _ = opsq.weight_bytes(eng_f.trainer.params)
    wb_q, wb_q32 = opsq.weight_bytes(eng_q.trainer.params)
    out = {
        "model": args.model,
        "dev": args.dev,
        "rows_per_request": args.rows,
        "max_batch_size": args.max_batch,
        "quant_ab": {
            "scheme": args.quant,
            "f32": f32,
            "quant": qnt,
            "speedup": (qnt["req_per_sec"] / f32["req_per_sec"]
                        if f32["req_per_sec"] > 0 else 0.0),
            "weight_bytes_f32": wb_f,
            "weight_bytes_quant": wb_q,
            "bytes_ratio": (wb_q32 / wb_q) if wb_q else 0.0,
        },
    }
    eng_f.close()
    eng_q.close()
    return out


def run_autotune(args) -> dict:
    """Bad-knobs recovery for the serve plane: start the micro-batcher
    at deliberately bad settings (batch 1, 1 ms window), drive
    closed-loop traffic while the self-tuning controller
    (``cxxnet_tpu/tune``) retunes it — with speculative bucket prewarm
    compiling each bigger bucket BEFORE it goes live — then re-measure
    cleanly and compare against the hand-tuned defaults.  The TUNE=1
    lane asserts ``recovery_ratio >= threshold``."""
    import threading as _thr

    from cxxnet_tpu.tune import KnobController, batcher_knobs

    # hand-tuned reference engine: the defaults (max-batch capacity,
    # 2 ms).  Built and warmed now, MEASURED at the end interleaved
    # with the tuned engine — measuring the two legs ~30 s apart made
    # the recovery ratio hostage to machine-load drift between the
    # windows (the same fix io_bench.run_autotune carries).
    hand_eng, x = build_engine(args)
    for _ in range(8):
        hand_eng.predict(x)

    # bad knobs + controller; a fresh engine so stats stay per-leg
    eng2, x = build_engine(args)
    eng2.set_max_batch_size(1, prewarm=False)
    eng2.set_batch_timeout_ms(1.0)
    for _ in range(8):
        eng2.predict(x)
    bad = closed_loop(eng2, x, args.concurrency,
                      max(8, args.requests // 8))
    ctrl = KnobController(
        lambda: float(eng2.stats.batch_rows), batcher_knobs(eng2),
        period_s=args.tune_period, band=args.tune_band,
        name="serve_bench", on_tick=eng2.prewarm_buckets,
    )
    stop_evt = _thr.Event()

    def _traffic():
        while not stop_evt.is_set():
            try:
                eng2.predict(x)
            except Exception:
                time.sleep(0.01)

    threads = [_thr.Thread(target=_traffic, daemon=True)
               for _ in range(args.concurrency)]
    ctrl.start()
    for t in threads:
        t.start()
    time.sleep(args.autotune_seconds)
    ctrl.stop()
    stop_evt.set()
    for t in threads:
        t.join(timeout=5.0)
    snap = ctrl.snapshot()
    # interleaved clean re-measures: tuned / hand / tuned / hand, back
    # to back, best-of per leg — drift hits both legs equally
    half = max(8, args.requests // 2)
    tuned_runs, hand_runs = [], []
    for _ in range(2):
        tuned_runs.append(closed_loop(eng2, x, args.concurrency, half))
        hand_runs.append(closed_loop(hand_eng, x, args.concurrency, half))
    final = max(tuned_runs, key=lambda r: r["req_per_sec"])
    hand = max(hand_runs, key=lambda r: r["req_per_sec"])
    stats = eng2.snapshot_stats()
    eng2.close()
    hand_eng.close()
    recovery = (final["req_per_sec"] / hand["req_per_sec"]
                if hand["req_per_sec"] > 0 else 0.0)
    threshold = args.recovery
    return {
        "model": args.model,
        "dev": args.dev,
        "rows_per_request": args.rows,
        "closed_loop": {"concurrent": final},
        "autotune": {
            "seconds": args.autotune_seconds,
            "period_s": args.tune_period,
            "band": args.tune_band,
            "initial": {"max_batch_size": 1, "batch_timeout_ms": 1.0,
                        "req_per_sec": bad["req_per_sec"],
                        "p50_ms": bad["latency_ms"]["p50"]},
            "hand": {"max_batch_size": args.max_batch,
                     "batch_timeout_ms": args.timeout_ms,
                     "req_per_sec": hand["req_per_sec"],
                     "p50_ms": hand["latency_ms"]["p50"]},
            "tuned": {"max_batch_size": snap["knobs"]["max_batch_size"],
                      "batch_timeout_ms":
                          snap["knobs"]["batch_timeout_ms"],
                      "req_per_sec": final["req_per_sec"],
                      "p50_ms": final["latency_ms"]["p50"]},
            "controller": snap,
            "recovery_ratio": recovery,
            "threshold": threshold,
            "ok": bool(recovery >= threshold),
        },
        "serving_stats": stats,
    }


def validate_autotune(doc: dict) -> None:
    """Schema check for the serve ``--autotune`` verdict (the TUNE=1
    lane's contract); raises ValueError on drift."""
    import math

    at = doc.get("autotune")
    if not isinstance(at, dict):
        raise ValueError("serve autotune report: missing autotune section")
    for key in ("initial", "hand", "tuned", "recovery_ratio",
                "threshold", "ok", "controller"):
        if key not in at:
            raise ValueError(f"serve autotune report: missing {key!r}")
    for leg in ("initial", "hand", "tuned"):
        for field in ("req_per_sec", "p50_ms"):
            v = at[leg].get(field)
            if not (isinstance(v, (int, float)) and math.isfinite(v)
                    and v > 0):
                raise ValueError(
                    f"serve autotune report: bad {leg}.{field} {v!r}")
    conc = doc.get("closed_loop", {}).get("concurrent", {})
    if "req_per_sec" not in conc:
        raise ValueError(
            "serve autotune report: closed_loop.concurrent missing")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--model", default="mnist_mlp")
    ap.add_argument("--dev", default=os.environ.get("BENCH_DEV", "cpu"))
    ap.add_argument("--concurrency", type=int, default=16)
    ap.add_argument("--requests", type=int, default=200,
                    help="closed-loop requests per thread")
    ap.add_argument("--rows", type=int, default=1,
                    help="rows per request")
    ap.add_argument("--max-batch", type=int, default=64)
    ap.add_argument("--timeout-ms", type=float, default=2.0)
    ap.add_argument("--open-rates", default="",
                    help="comma-separated offered req/s for open-loop runs")
    ap.add_argument("--open-duration", type=float, default=3.0,
                    dest="open_duration",
                    help="seconds per open-loop run (and the burst "
                         "profile's total duration)")
    ap.add_argument("--duration", type=float, dest="open_duration",
                    default=argparse.SUPPRESS,
                    help="alias of --open-duration for the burst mode")
    ap.add_argument("--open-loop", action="store_true",
                    help="run the open-loop driver (with --burst: the "
                         "square-wave burst profile)")
    ap.add_argument("--burst", action="store_true",
                    help="burst profile: alternate --base-rate and "
                         "--burst-rate every --phase seconds")
    ap.add_argument("--base-rate", type=float, default=100.0)
    ap.add_argument("--burst-rate", type=float, default=400.0)
    ap.add_argument("--phase", type=float, default=1.0,
                    help="seconds per burst-profile phase")
    ap.add_argument("--total-requests", type=int, default=0,
                    help="stop after this many arrivals instead of "
                         "--duration (the >= 10^6-request story)")
    ap.add_argument("--clients", type=int, default=64,
                    help="burst-driver worker pool size")
    ap.add_argument("--progress-s", type=float, default=0.0,
                    help="stream running burst counts + p50/p99 to "
                         "stderr every N seconds (0 = off)")
    ap.add_argument("--deadline-ms", type=float, default=0.0,
                    help="per-request deadline for the burst driver")
    ap.add_argument("--url", default="",
                    help="drive a running HTTP front end (fleet router "
                         "or single server) instead of the in-process "
                         "engine")
    ap.add_argument("--wire", default="json",
                    choices=("json", "binary"),
                    help="wire format for the --url client (binary = "
                         "CXB1 frames, doc/serving.md)")
    ap.add_argument("--wire-ab", action="store_true",
                    help="JSON-vs-binary closed-loop A/B over HTTP "
                         "(WIRE=1 lane); exits 1 if the score parity "
                         "check fails")
    ap.add_argument("--json", dest="json_path", default="",
                    help="also write the JSON report here")
    ap.add_argument("--quant", default="",
                    help="run the f32-vs-quantized A/B at this scheme "
                         "(int8|bf16) instead of the plain bench")
    ap.add_argument("--autotune", action="store_true",
                    help="bad-knobs recovery via the tune controller "
                         "(TUNE=1 lane); exits 1 below --recovery")
    ap.add_argument("--autotune-seconds", type=float, default=15.0)
    ap.add_argument("--tune-period", type=float, default=0.5)
    ap.add_argument("--tune-band", type=float, default=0.1)
    ap.add_argument("--recovery", type=float, default=0.9,
                    help="autotune pass bar vs the hand-tuned rate")
    args = ap.parse_args(argv)

    if args.open_loop and args.burst:
        result = run_open_loop_burst(args)
        b = result["open_loop_burst"]
        print(json.dumps(result, indent=1))
        if args.json_path:
            with open(args.json_path, "w", encoding="utf-8") as f:
                json.dump(result, f, indent=1)
        lat = b.get("latency_ms", {})
        print(f"bench[burst:{args.model}] sent {b['sent']} "
              f"ok {b['completed']} shed {b['shed']} "
              f"expired {b['expired']} err {b['errors']} "
              f"achieved {b['achieved_req_per_sec']:.1f} req/s "
              f"p50 {lat.get('p50', float('nan')):.2f} ms "
              f"p99 {lat.get('p99', float('nan')):.2f} ms",
              file=sys.stderr, flush=True)
        return 0 if b["errors"] == 0 else 1

    if args.wire_ab:
        result = run_wire_ab(args)
        ab = result["wire_ab"]
        print(json.dumps(result, indent=1))
        if args.json_path:
            with open(args.json_path, "w", encoding="utf-8") as f:
                json.dump(result, f, indent=1)
        print(f"bench[wire_ab:{args.model}] json "
              f"{ab['json']['req_per_sec']:.1f} req/s vs binary "
              f"{ab['binary']['req_per_sec']:.1f} req/s speedup "
              f"{ab['speedup']:.3f} parity "
              f"{'ok' if ab['bitwise_equal_scores'] else 'FAIL'} "
              f"p99 {ab['json']['latency_ms']['p99']:.2f} -> "
              f"{ab['binary']['latency_ms']['p99']:.2f} ms",
              flush=True)
        return 0 if ab["bitwise_equal_scores"] else 1

    if args.quant:
        result = run_quant_ab(args)
        ab = result["quant_ab"]
        print(json.dumps(result, indent=1))
        if args.json_path:
            with open(args.json_path, "w", encoding="utf-8") as f:
                json.dump(result, f, indent=1)
        # the bench[...] spelling is what the TPU queue's relay-log grep
        # keeps (tools/tpu_queue.sh) — one self-contained verdict line
        print(f"bench[quant_ab:{args.model}] f32 "
              f"{ab['f32']['req_per_sec']:.1f} req/s vs {ab['scheme']} "
              f"{ab['quant']['req_per_sec']:.1f} req/s speedup "
              f"{ab['speedup']:.3f} bytes_ratio {ab['bytes_ratio']:.2f} "
              f"p99 {ab['f32']['latency_ms']['p99']:.2f} -> "
              f"{ab['quant']['latency_ms']['p99']:.2f} ms",
              flush=True)
        return 0

    if args.autotune:
        result = run_autotune(args)
        validate_autotune(result)
        at = result["autotune"]
        print(json.dumps(result, indent=1))
        if args.json_path:
            with open(args.json_path, "w", encoding="utf-8") as f:
                json.dump(result, f, indent=1)
        print(f"# autotune: bad {at['initial']['req_per_sec']:.0f} req/s "
              f"-> tuned {at['tuned']['req_per_sec']:.0f} req/s "
              f"(batch={at['tuned']['max_batch_size']}, "
              f"timeout={at['tuned']['batch_timeout_ms']:.2f}ms) vs hand "
              f"{at['hand']['req_per_sec']:.0f} req/s: recovery "
              f"{at['recovery_ratio']:.2f} "
              f"({'OK' if at['ok'] else 'FAIL'} at >= {at['threshold']})",
              file=sys.stderr, flush=True)
        return 0 if at["ok"] else 1

    eng, x = build_engine(args)
    for _ in range(8):
        eng.predict(x)  # warm the bucket + compile

    seq = closed_loop(eng, x, concurrency=1, requests=args.requests)
    conc = closed_loop(eng, x, concurrency=args.concurrency,
                       requests=args.requests)
    result = {
        "model": args.model,
        "dev": args.dev,
        "rows_per_request": args.rows,
        "max_batch_size": args.max_batch,
        "batch_timeout_ms": args.timeout_ms,
        "closed_loop": {
            "sequential": seq,
            "concurrent": conc,
            "speedup": conc["req_per_sec"] / seq["req_per_sec"],
        },
    }
    rates = [float(r) for r in args.open_rates.split(",") if r.strip()]
    if rates:
        result["open_loop"] = [
            open_loop(eng, x, rate, args.open_duration) for rate in rates
        ]
    result["serving_stats"] = eng.snapshot_stats()
    eng.close()
    print(json.dumps(result, indent=1))
    if args.json_path:
        with open(args.json_path, "w", encoding="utf-8") as f:
            json.dump(result, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
