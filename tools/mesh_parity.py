"""MESH=1 lane: 4-process CPU-mesh bitwise parity + compile-count guard.

The pod-scale SPMD claim (ROADMAP item 1), proven end to end through the
real CLI on the MNIST MLP conf:

* **bitwise parity** — a 4-process ``jax.distributed`` job over a
  4-device CPU mesh trains the same conf as a single-process run of the
  SAME mesh (4 virtual devices), same seed, same rounds, iterators
  sharding contiguously (``dist_shard = block``); every checkpoint the
  two runs write must carry IDENTICAL manifest CRC32s.  One compiler-
  partitioned program + one collectives implementation (gloo) means the
  gradient reduction order — and therefore every weight bit — cannot
  depend on the process layout;
* **compile-count guard** — each process must compile the SAME number
  of XLA programs as the single-process run compiles (no per-replica
  re-jits: the mesh step is ONE program whatever the layout), counted
  exactly by the ``jax.monitoring`` backend-compile listener
  (``telemetry=1`` device summaries);
* the verdict JSON appends to a ``perf_guard`` history
  (``--bench mesh_parity``), so a future change that starts re-jitting
  per replica or slows the mesh step trips the regression sentinel.

Usage::

    python tools/mesh_parity.py --out /tmp/_mesh        # the CI lane
    python tools/perf_guard.py --bench mesh_parity \\
        --input /tmp/_mesh/mesh_parity.json --history bench_history.jsonl

Exit code: 0 when CRCs match bitwise and compile counts agree; 1
otherwise (the lane is a hard gate, not weather).
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

NUM_ROUND = 2
GLOBAL_BATCH = 32
N_IMAGES = 128


def _free_port() -> int:
    from cxxnet_tpu.parallel.elastic import free_port

    return free_port()


def make_data(out_dir: str) -> None:
    import numpy as np

    from cxxnet_tpu.io.mnist import write_idx_images, write_idx_labels

    rng = np.random.RandomState(0)
    imgs = rng.randint(0, 256, (N_IMAGES, 4, 4)).astype(np.uint8)
    labels = (imgs.reshape(N_IMAGES, -1).mean(1) > 127).astype(np.uint8)
    write_idx_images(os.path.join(out_dir, "img.idx"), imgs)
    write_idx_labels(os.path.join(out_dir, "lab.idx"), labels)


def make_conf(out_dir: str) -> str:
    """The MNIST MLP conf both runs share; per-run keys ride as CLI
    overrides.  ``dist_shard = block``: each rank's local batch is its
    contiguous slice of the global batch — the row order the SPMD
    global array assembles, and the bitwise-parity precondition."""
    conf = os.path.join(out_dir, "mesh.conf")
    with open(conf, "w", encoding="utf-8") as f:
        f.write(f"""
data = train
iter = mnist
  path_img = "{out_dir}/img.idx"
  path_label = "{out_dir}/lab.idx"
  shuffle = 1
  dist_shard = block
iter = end
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 8
  init_sigma = 0.1
layer[fc1->out] = fullc:fc2
  nhidden = 2
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,16
batch_size = {GLOBAL_BATCH}
dev = cpu
num_round = {NUM_ROUND}
eval_train = 0
eta = 0.1
momentum = 0.9
seed = 7
shard_weight_update = 1
metric = error
silent = 1
telemetry = 1
""")
    return conf


def run_job(conf: str, workdir: str, nproc: int, port: int,
            timeout: float) -> None:
    """Launch one parity side: ``nproc`` CLI processes (1 device each),
    or one process holding the whole 4-device mesh.  BOTH initialize
    jax.distributed (the 1-process run with num_processes=1) so the
    collectives implementation — and the all-reduce order — match."""
    ndev = 4 // nproc
    env = {
        **os.environ,
        "PYTHONPATH": REPO,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": f"--xla_force_host_platform_device_count={ndev}",
    }
    procs, dirs = [], []
    for r in range(nproc):
        d = os.path.join(workdir, f"p{r}")
        os.makedirs(d, exist_ok=True)
        dirs.append(d)
        over = [f"dist_coordinator=localhost:{port}",
                f"dist_num_proc={nproc}", f"dist_proc_id={r}"]
        if nproc == 1:
            over.append("dev=cpu:0-3")
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "cxxnet_tpu", conf] + over,
            env=env, cwd=d,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        ))
    try:
        outs = [p.communicate(timeout=timeout)[0] for p in procs]
    finally:
        for p in procs:  # bound the damage when a rank hangs
            if p.poll() is None:
                p.kill()
    for p, o in zip(procs, outs):
        if p.returncode != 0:
            raise RuntimeError(
                f"mesh_parity: rank process failed "
                f"(rc={p.returncode}):\n{o.decode()[-4000:]}")


def read_crcs(rank_dir: str) -> dict:
    """{round: manifest crc32} for every checkpoint a run wrote."""
    from cxxnet_tpu.utils import checkpoint as ckpt

    out = {}
    mdir = os.path.join(rank_dir, "models")
    for round_, path in ckpt.list_checkpoints(mdir):
        man = ckpt.read_manifest(path)
        if man is not None:
            out[round_] = man["crc32"]
    return out


def read_device_summary(rank_dir: str) -> dict:
    """Final telemetry record's device block (compiles / programs);
    ``{}`` when the run wrote no telemetry — the caller treats missing
    counts as a FAILURE (a gate that cannot read its signal must not
    pass vacuously)."""
    path = os.path.join(rank_dir, "telemetry.jsonl")
    last = None
    try:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line:
                    last = json.loads(line)
    except (OSError, ValueError):
        return {}
    return (last or {}).get("device") or {}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="/tmp/_mesh_parity",
                    help="scratch + verdict directory")
    ap.add_argument("--timeout", type=float, default=240.0,
                    help="per-side wall-clock budget (seconds)")
    ap.add_argument("--json", dest="json_path", default="",
                    help="verdict path (default <out>/mesh_parity.json)")
    args = ap.parse_args()

    os.makedirs(args.out, exist_ok=True)
    make_data(args.out)
    conf = make_conf(args.out)

    t0 = time.time()
    multi_dir = os.path.join(args.out, "multi")
    run_job(conf, multi_dir, nproc=4, port=_free_port(),
            timeout=args.timeout)
    multi_s = time.time() - t0
    t1 = time.time()
    single_dir = os.path.join(args.out, "single")
    run_job(conf, single_dir, nproc=1, port=_free_port(),
            timeout=args.timeout)
    single_s = time.time() - t1

    problems = []
    multi_crcs = [read_crcs(os.path.join(multi_dir, f"p{r}"))
                  for r in range(4)]
    single_crcs = read_crcs(os.path.join(single_dir, "p0"))
    if not single_crcs or len(single_crcs) != NUM_ROUND + 1:
        problems.append(
            f"single run wrote {sorted(single_crcs)} rounds, expected "
            f"{NUM_ROUND + 1} checkpoints")
    for r in range(1, 4):
        # rank-0-writes discipline: the peers run in their own working
        # dirs and must have written NO checkpoints of their own
        if multi_crcs[r]:
            problems.append(
                f"multi rank {r} wrote its own checkpoints "
                f"{sorted(multi_crcs[r])} — violates the rank-0-writes "
                "discipline")
    if multi_crcs[0] != single_crcs:
        problems.append(
            f"BITWISE PARITY FAILED: 4-process CRCs {multi_crcs[0]} != "
            f"single-process CRCs {single_crcs}")

    multi_dev = [read_device_summary(os.path.join(multi_dir, f"p{r}"))
                 for r in range(4)]
    single_dev = read_device_summary(os.path.join(single_dir, "p0"))
    compiles = [d.get("compiles") for d in multi_dev]
    programs = [d.get("programs") for d in multi_dev]
    # missing counts FAIL the gate — all-None would otherwise satisfy
    # both equality checks and let the guard pass vacuously
    if any(c is None for c in compiles) or single_dev.get(
            "compiles") is None:
        problems.append(
            f"compile counts unreadable (multi {compiles}, single "
            f"{single_dev.get('compiles')}) — telemetry device block "
            "missing; the compile-count gate cannot run")
    elif len(set(compiles)) != 1:
        problems.append(f"per-rank compile counts differ: {compiles} — "
                        "a rank re-jitted (not one program)")
    if any(p is None for p in programs) or single_dev.get(
            "programs") is None:
        problems.append(
            f"program counts unreadable (multi {programs}, single "
            f"{single_dev.get('programs')}) — telemetry device block "
            "missing; the one-program gate cannot run")
    elif len(set(programs)) != 1 or programs[0] != single_dev.get(
            "programs"):
        problems.append(
            f"instrumented train programs differ across layouts: "
            f"multi {programs} vs single {single_dev.get('programs')}")

    doc = {
        "bench": "mesh_parity",
        "ts": time.time(),
        "rounds": NUM_ROUND,
        "global_batch": GLOBAL_BATCH,
        "crc_equal": multi_crcs[0] == single_crcs,
        "crcs": {str(k): f"{v:#010x}" for k, v in sorted(
            single_crcs.items())},
        "multi": {"wall_sec": round(multi_s, 3),
                  "compiles": compiles[0],
                  "programs": programs[0]},
        "single": {"wall_sec": round(single_s, 3),
                   "compiles": single_dev.get("compiles"),
                   "programs": single_dev.get("programs")},
        "problems": problems,
        "verdict": "ok" if not problems else "fail",
    }
    json_path = args.json_path or os.path.join(args.out,
                                               "mesh_parity.json")
    with open(json_path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1)
    print(json.dumps(doc, indent=1))
    for p in problems:
        print(f"FAIL {p}", file=sys.stderr)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
