"""Write a REAL handwritten-digit dataset in MNIST idx format.

The image has zero network egress, so the MNIST ubyte files the
reference's example downloads (``/root/reference/example/MNIST/README.md``)
cannot be fetched.  scikit-learn bundles the UCI ML handwritten digits
set — 1797 real 8x8 handwritten digit scans — which serves as the
real-data accuracy fixture: idx-encoded here, trained by the CLI via
``example/MNIST/digits.conf`` (same MLP recipe as MNIST.conf) to the
published error in README.md.

Usage: python tools/make_digits_idx.py <outdir> [n_test]
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def write_digits_idx(outdir: str, n_test: int = 297) -> None:
    from sklearn.datasets import load_digits

    from cxxnet_tpu.io.mnist import write_idx_images, write_idx_labels

    d = load_digits()
    # pixels are 0..16; idx stores uint8 and the reader scales by 1/256
    imgs = np.clip(d.images * 16, 0, 255).astype(np.uint8)
    labels = d.target.astype(np.uint8)
    rng = np.random.RandomState(0)
    perm = rng.permutation(len(labels))
    imgs, labels = imgs[perm], labels[perm]
    os.makedirs(outdir, exist_ok=True)
    write_idx_images(
        os.path.join(outdir, "digits-train-images-idx3-ubyte"), imgs[n_test:]
    )
    write_idx_labels(
        os.path.join(outdir, "digits-train-labels-idx1-ubyte"), labels[n_test:]
    )
    write_idx_images(
        os.path.join(outdir, "digits-t10k-images-idx3-ubyte"), imgs[:n_test]
    )
    write_idx_labels(
        os.path.join(outdir, "digits-t10k-labels-idx1-ubyte"), labels[:n_test]
    )
    print(
        f"wrote {len(labels) - n_test} train / {n_test} test real "
        f"handwritten digits (8x8 idx) to {outdir}"
    )


if __name__ == "__main__":
    write_digits_idx(
        sys.argv[1] if len(sys.argv) > 1 else "example/MNIST/data",
        int(sys.argv[2]) if len(sys.argv) > 2 else 297,
    )
