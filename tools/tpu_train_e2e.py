"""End-to-end ON-CHIP training with IO in-path (VERDICT r3 #4).

The reference's actual operating mode (``cxxnet_main.cpp:344-403``):
JPEG shards -> decode -> augment -> batch -> train loop, as opposed to
the synthetic-data device-rate bench.  Generates an imgbin shard set,
writes a conf that feeds GoogLeNet through the real pipeline (native
decode pool + threadbuffer + chunked async scan), runs ``task=train``
for a few rounds via the CLI, and leaves the log for committing to
``example/ImageNet/``.

Run through the serialized queue (tools/tpu_queue.sh) only:

    python tools/tpu_train_e2e.py [n_images] [rounds] [batch]
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

CACHE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), ".jax_cache"
)


def main() -> None:
    import jax

    os.makedirs(CACHE_DIR, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", CACHE_DIR)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

    from io_bench import generate_imgbin

    from cxxnet_tpu.cli import LearnTask
    from cxxnet_tpu.models import googlenet_conf

    n_img = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
    rounds = int(sys.argv[2]) if len(sys.argv) > 2 else 3
    batch = int(sys.argv[3]) if len(sys.argv) > 3 else 128

    with tempfile.TemporaryDirectory() as workdir:
        generate_imgbin(workdir, n_img, 256)
        # small eval split from the same shard (pipeline parity is the
        # point here, not held-out accuracy)
        conf = f"""
data = train
iter = imgbin
  image_bin = {workdir}/bench.bin
  image_list = {workdir}/bench.lst
  rand_crop = 1
  rand_mirror = 1
  input_shape = 3,224,224
  batch_size = {batch}
  round_batch = 1
  label_width = 1
iter = threadbuffer
iter = end
eval = test
iter = imgbin
  image_bin = {workdir}/bench.bin
  image_list = {workdir}/bench.lst
  input_shape = 3,224,224
  batch_size = {batch}
  round_batch = 1
  label_width = 1
iter = end
""" + googlenet_conf(batch_size=batch, input_size=224, synthetic=False,
                     dev="tpu") + f"""
num_round = {rounds}
scan_steps = 8
print_step = 1
model_dir = {workdir}/models
"""
        conf_path = os.path.join(workdir, "e2e.conf")
        with open(conf_path, "w") as f:
            f.write(conf)
        LearnTask().run([conf_path])


if __name__ == "__main__":
    main()
