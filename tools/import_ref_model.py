"""Import a reference cxxnet binary ``.model`` checkpoint.

Migration path for reference users: the original ``.conf`` (which the
reference itself also requires at load time — ``LoadNet`` restores only
structure + weights, per-layer configs come from the conf,
``/root/reference/src/cxxnet_main.cpp:159-170``) plus the binary
``.model`` produce a cxxnet-tpu checkpoint with identical weights.

    python tools/import_ref_model.py <conf> <ref.model> <out.model>

Binary layout (all little-endian; cited from the reference sources):

* ``int32 net_type``                       (cxxnet_main.cpp:177)
* ``NetConfig::NetParam`` — 4 int32 fields (num_nodes, num_layers,
  init_end, extra_data_num) + 31 reserved int32 (nnet_config.h:28-41)
* if extra_data_num: ``vector<int> extra_shape`` (uint64 count +
  int32s, utils/io.h:43-48)
* ``num_nodes`` x string (uint64 len + bytes, utils/io.h:69-74)
* ``num_layers`` x { int32 LayerType, int32 primary_layer_index,
  string name, vector<int32> nindex_in, vector<int32> nindex_out }
  (nnet_config.h:126-145)
* ``int64 epoch_counter``                  (nnet_impl-inl.hpp:85,420)
* ``string model_blob`` — concatenated per-layer payloads, only for
  layers that override SaveModel (layer sources):
  - fullc:      LayerParam + wmat(2d) + bias(1d)   (fullc_layer:46-50)
  - conv:       LayerParam + wmat(3d) + bias(1d)   (convolution_layer)
  - bias:       LayerParam + bias(1d)              (bias_layer)
  - batch_norm: slope(1d) + bias(1d)               (batch_norm_layer)
  - prelu:      slope(1d)                          (prelu_layer)
  LayerParam = 18 int32/float32 fields + 64 reserved int32 = 328 bytes
  (layer/param.h:15-53).

mshadow ``SaveBinary`` writes ``Shape<dim>`` then the row-contiguous
f32 data.  Depending on the mshadow revision the reference was built
against, ``sizeof(Shape<dim>)`` is either ``dim`` uint32s (shape only)
or ``dim+1`` (a trailing ``stride_``); the parser tries the shape-only
encoding first and falls back — each layer's expected element count is
derivable from its LayerParam, so a wrong hypothesis fails loudly
instead of misreading.
"""

from __future__ import annotations

import os
import struct
import sys
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

# reference layer.h:284-313
LAYER_TYPES = {
    0: "shared", 1: "fullc", 2: "softmax", 3: "relu", 4: "sigmoid",
    5: "tanh", 6: "softplus", 7: "flatten", 8: "dropout", 10: "conv",
    11: "max_pooling", 12: "sum_pooling", 13: "avg_pooling", 15: "lrn",
    17: "bias", 18: "concat", 19: "xelu", 20: "caffe",
    21: "relu_max_pooling", 23: "split", 24: "insanity",
    25: "insanity_max_pooling", 26: "l2_loss", 27: "multi_logistic",
    28: "ch_concat", 29: "prelu", 30: "batch_norm", 31: "fixconn",
}
PAIRTEST_GAP = 1024  # layer.h:315

LAYER_PARAM_BYTES = (18 + 64) * 4  # param.h:15-53


class Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.at = 0

    def raw(self, n: int) -> bytes:
        if self.at + n > len(self.data):
            raise ValueError(
                f"truncated reference model: need {n} bytes at "
                f"offset {self.at}, have {len(self.data) - self.at}"
            )
        out = self.data[self.at:self.at + n]
        self.at += n
        return out

    def i32(self) -> int:
        return struct.unpack("<i", self.raw(4))[0]

    def i64(self) -> int:
        return struct.unpack("<q", self.raw(8))[0]

    def u32s(self, n: int) -> Tuple[int, ...]:
        return struct.unpack(f"<{n}I", self.raw(4 * n))

    def string(self) -> bytes:
        (n,) = struct.unpack("<Q", self.raw(8))
        return self.raw(n)

    def vec_i32(self) -> List[int]:
        (n,) = struct.unpack("<Q", self.raw(8))
        return list(struct.unpack(f"<{n}i", self.raw(4 * n)))

    def f32_array(self, count: int) -> np.ndarray:
        return np.frombuffer(self.raw(4 * count), "<f4").copy()

    def done(self) -> bool:
        return self.at == len(self.data)


def _read_layer_param(r: Reader) -> Dict[str, int]:
    """The handful of LayerParam fields the importer needs (param.h
    field order; floats skipped positionally)."""
    raw = r.raw(LAYER_PARAM_BYTES)
    ints = struct.unpack("<82i", raw)
    return {
        "num_hidden": ints[0], "num_channel": ints[5],
        "num_group": ints[7], "kernel_height": ints[8],
        "kernel_width": ints[9], "no_bias": ints[13],
        "num_input_node": ints[17],
    }


def _read_tensor(r: Reader, dim: int, with_stride: bool,
                 expect: Optional[Tuple[int, ...]] = None) -> np.ndarray:
    shape = r.u32s(dim)
    if with_stride:
        r.u32s(1)  # Shape<dim>::stride_ — not needed, rows are contiguous
    if expect is not None and tuple(shape) != tuple(expect):
        raise ValueError(
            f"tensor shape {shape} != expected {expect} "
            "(wrong mshadow Shape encoding?)"
        )
    if any(s <= 0 or s > 1 << 28 for s in shape):
        raise ValueError(f"implausible tensor shape {shape}")
    n = int(np.prod(shape))
    return r.f32_array(n).reshape(shape)


def parse_ref_model(path: str, with_stride: Optional[bool] = None):
    """-> (net_type, layer_infos, epoch, weights) where layer_infos is
    [{type_id, type_name, primary, name, nin, nout}] and weights is
    {layer_name: {tag: np.ndarray}} in the reference's native layouts."""
    blob = open(path, "rb").read()
    r = Reader(blob)
    net_type = r.i32()
    num_nodes, num_layers, _init_end, extra_data_num = (
        r.i32(), r.i32(), r.i32(), r.i32())
    r.raw(31 * 4)  # NetParam.reserved
    if not (0 < num_nodes < 1 << 20 and 0 < num_layers < 1 << 20):
        raise ValueError(f"{path}: not a reference cxxnet model "
                         f"(nodes={num_nodes}, layers={num_layers})")
    if extra_data_num:
        r.vec_i32()
    node_names = [r.string().decode() for _ in range(num_nodes)]
    infos = []
    for _ in range(num_layers):
        tid = r.i32()
        primary = r.i32()
        name = r.string().decode()
        nin = r.vec_i32()
        nout = r.vec_i32()
        base = tid - PAIRTEST_GAP if tid >= PAIRTEST_GAP else tid
        if base not in LAYER_TYPES:
            raise ValueError(f"unknown reference LayerType {tid}")
        infos.append({
            "type_id": tid, "type_name": LAYER_TYPES[base],
            "primary": primary, "name": name, "nin": nin, "nout": nout,
        })
    epoch = r.i64()
    model_blob = r.string()

    if with_stride is None:
        # disambiguate the mshadow Shape encoding on the actual payload
        try:
            weights = _parse_blob(model_blob, infos, with_stride=False)
        except ValueError:
            weights = _parse_blob(model_blob, infos, with_stride=True)
    else:
        weights = _parse_blob(model_blob, infos, with_stride)
    return net_type, node_names, infos, epoch, weights


def _parse_blob(blob: bytes, infos, with_stride: bool):
    r = Reader(blob)
    weights: Dict[str, Dict[str, np.ndarray]] = {}
    for li, info in enumerate(infos):
        t = info["type_name"]
        if info["type_id"] >= PAIRTEST_GAP:
            raise ValueError("pairtest checkpoints are not importable "
                             "(debug-only composition)")
        key = info["name"] or f"layer{li}"
        if t == "fullc":
            p = _read_layer_param(r)
            w = _read_tensor(r, 2, with_stride,
                             (p["num_hidden"], p["num_input_node"]))
            b = _read_tensor(r, 1, with_stride, (p["num_hidden"],))
            weights[key] = {"wmat": w, "bias": b, "_param": p}
        elif t == "conv":
            p = _read_layer_param(r)
            g = max(1, p["num_group"])
            cout_g = p["num_channel"] // g
            w = _read_tensor(r, 3, with_stride)
            if w.shape[0] != g or w.shape[1] != cout_g:
                raise ValueError(
                    f"conv {key}: wmat shape {w.shape} inconsistent with "
                    f"LayerParam (g={g}, cout_g={cout_g})"
                )
            b = _read_tensor(r, 1, with_stride, (p["num_channel"],))
            weights[key] = {"wmat": w, "bias": b, "_param": p}
        elif t == "bias":
            p = _read_layer_param(r)
            weights[key] = {
                "bias": _read_tensor(r, 1, with_stride), "_param": p}
        elif t == "batch_norm":
            s = _read_tensor(r, 1, with_stride)
            b = _read_tensor(r, 1, with_stride, tuple(s.shape))
            weights[key] = {"wmat": s, "bias": b}
        elif t == "prelu":
            weights[key] = {"bias": _read_tensor(r, 1, with_stride)}
        # every other type saves nothing (layer.h:273 default)
    if not r.done():
        raise ValueError(
            f"model blob has {len(blob) - r.at} unconsumed bytes — "
            "wrong Shape encoding or unsupported layer payload"
        )
    return weights


def install(tr, infos, weights) -> int:
    """Install parsed reference weights into a conf-built NetTrainer,
    checking the binary's graph against the conf's."""
    g = tr.graph
    ref_named = {i["name"]: i for i in infos if i["name"]}
    n_set = 0
    for i, spec in enumerate(g.layers):
        if not spec.name or spec.name not in ref_named:
            continue
        info = ref_named[spec.name]
        if info["type_name"] != spec.type_name and spec.type_name != "shared":
            raise ValueError(
                f"layer {spec.name}: conf says {spec.type_name}, "
                f"reference model says {info['type_name']}"
            )
        w = weights.get(spec.name)
        if not w:
            continue
        if spec.type_name == "conv":
            p = w["_param"]
            gg = max(1, p["num_group"])
            # (g, cout_g, cin_g*kh*kw) -> the visitor's (cout, cin_g*kh*kw)
            tr.set_weight(w["wmat"].reshape(gg * w["wmat"].shape[1], -1),
                          spec.name, "wmat")
            if not p["no_bias"]:
                tr.set_weight(w["bias"], spec.name, "bias")
        else:
            for tag in ("wmat", "bias"):
                if tag in w:
                    tr.set_weight(w[tag], spec.name, tag)
        n_set += 1
    if n_set == 0:
        raise ValueError(
            "no layer of the conf matched a weighted layer in the "
            "reference model — check that conf and model belong together"
        )
    return n_set


def main() -> None:
    if len(sys.argv) != 4:
        raise SystemExit(
            "usage: python tools/import_ref_model.py "
            "<conf> <ref.model> <out.model>"
        )
    conf_path, ref_path, out_path = sys.argv[1:]
    from cxxnet_tpu import config as cfgmod
    from cxxnet_tpu.nnet.trainer import NetTrainer

    net_type, _nodes, infos, epoch, weights = parse_ref_model(ref_path)
    print(f"reference model: net_type={net_type}, {len(infos)} layers, "
          f"{len(weights)} weighted, epoch_counter={epoch}")
    entries = cfgmod.parse_file(conf_path)
    sections = cfgmod.split_sections(entries)
    tr = NetTrainer()
    tr.set_params(sections.global_entries)
    tr.init_model()
    n = install(tr, infos, weights)
    tr.save_model(out_path)
    print(f"installed {n} weighted layers -> {out_path}")


if __name__ == "__main__":
    main()
