"""Import a reference cxxnet binary ``.model`` checkpoint.

Migration path for reference users: the original ``.conf`` (which the
reference itself also requires at load time — ``LoadNet`` restores only
structure + weights, per-layer configs come from the conf,
``/root/reference/src/cxxnet_main.cpp:159-170``) plus the binary
``.model`` produce a cxxnet-tpu checkpoint with identical weights.

    python tools/import_ref_model.py <conf> <ref.model> <out.model>

Binary layout (all little-endian; cited from the reference sources):

* ``int32 net_type``                       (cxxnet_main.cpp:177)
* ``NetConfig::NetParam`` — num_nodes, num_layers (int32),
  ``mshadow::Shape<3> input_shape`` (3 index_t words, or 4 under the
  stride-bearing mshadow revision), init_end, extra_data_num (int32)
  + 31 reserved int32 (nnet_config.h:28-50; SaveNet dumps the whole
  struct, nnet_config.h:127)
* if extra_data_num: ``vector<int> extra_shape`` (uint64 count +
  int32s, utils/io.h:43-48)
* ``num_nodes`` x string (uint64 len + bytes, utils/io.h:69-74)
* ``num_layers`` x { int32 LayerType, int32 primary_layer_index,
  string name, vector<int32> nindex_in, vector<int32> nindex_out }
  (nnet_config.h:126-145)
* ``int64 epoch_counter``                  (nnet_impl-inl.hpp:85,420)
* ``string model_blob`` — concatenated per-layer payloads, only for
  layers that override SaveModel (layer sources):
  - fullc:      LayerParam + wmat(2d) + bias(1d)   (fullc_layer:46-50)
  - conv:       LayerParam + wmat(3d) + bias(1d)   (convolution_layer)
  - bias:       LayerParam + bias(1d)              (bias_layer)
  - batch_norm: slope(1d) + bias(1d)               (batch_norm_layer)
  - prelu:      slope(1d)                          (prelu_layer)
  LayerParam = 18 int32/float32 fields + 64 reserved int32 = 328 bytes
  (layer/param.h:15-53).

mshadow ``SaveBinary`` writes ``Shape<dim>`` then the row-contiguous
f32 data.  Depending on the mshadow revision the reference was built
against, ``sizeof(Shape<dim>)`` is either ``dim`` uint32s (shape only)
or ``dim+1`` (a trailing ``stride_``); the parser tries the shape-only
encoding first and falls back — each layer's expected element count is
derivable from its LayerParam, so a wrong hypothesis fails loudly
instead of misreading.
"""

from __future__ import annotations

import os
import struct
import sys
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

# reference layer.h:284-313
LAYER_TYPES = {
    0: "shared", 1: "fullc", 2: "softmax", 3: "relu", 4: "sigmoid",
    5: "tanh", 6: "softplus", 7: "flatten", 8: "dropout", 10: "conv",
    11: "max_pooling", 12: "sum_pooling", 13: "avg_pooling", 15: "lrn",
    17: "bias", 18: "concat", 19: "xelu", 20: "caffe",
    21: "relu_max_pooling", 23: "split", 24: "insanity",
    25: "insanity_max_pooling", 26: "l2_loss", 27: "multi_logistic",
    28: "ch_concat", 29: "prelu", 30: "batch_norm", 31: "fixconn",
}
PAIRTEST_GAP = 1024  # layer.h:315

LAYER_PARAM_BYTES = (18 + 64) * 4  # param.h:15-53


class Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.at = 0

    def raw(self, n: int) -> bytes:
        if self.at + n > len(self.data):
            raise ValueError(
                f"truncated reference model: need {n} bytes at "
                f"offset {self.at}, have {len(self.data) - self.at}"
            )
        out = self.data[self.at:self.at + n]
        self.at += n
        return out

    def i32(self) -> int:
        return struct.unpack("<i", self.raw(4))[0]

    def i64(self) -> int:
        return struct.unpack("<q", self.raw(8))[0]

    def u32s(self, n: int) -> Tuple[int, ...]:
        return struct.unpack(f"<{n}I", self.raw(4 * n))

    def string(self) -> bytes:
        (n,) = struct.unpack("<Q", self.raw(8))
        return self.raw(n)

    def vec_i32(self) -> List[int]:
        (n,) = struct.unpack("<Q", self.raw(8))
        return list(struct.unpack(f"<{n}i", self.raw(4 * n)))

    def f32_array(self, count: int) -> np.ndarray:
        return np.frombuffer(self.raw(4 * count), "<f4").copy()

    def done(self) -> bool:
        return self.at == len(self.data)


def _read_layer_param(r: Reader) -> Dict[str, int]:
    """The handful of LayerParam fields the importer needs (param.h
    field order; floats skipped positionally)."""
    raw = r.raw(LAYER_PARAM_BYTES)
    ints = struct.unpack("<82i", raw)
    return {
        "num_hidden": ints[0], "num_channel": ints[5],
        "num_group": ints[7], "kernel_height": ints[8],
        "kernel_width": ints[9], "no_bias": ints[13],
        "num_input_node": ints[17],
    }


def _read_tensor(r: Reader, dim: int, with_stride: bool,
                 expect: Optional[Tuple[int, ...]] = None) -> np.ndarray:
    shape = r.u32s(dim)
    if with_stride:
        r.u32s(1)  # Shape<dim>::stride_ — not needed, rows are contiguous
    if expect is not None and tuple(shape) != tuple(expect):
        raise ValueError(
            f"tensor shape {shape} != expected {expect} "
            "(wrong mshadow Shape encoding?)"
        )
    if any(s <= 0 or s > 1 << 28 for s in shape):
        raise ValueError(f"implausible tensor shape {shape}")
    n = int(np.prod(shape))
    return r.f32_array(n).reshape(shape)


def parse_ref_model(path: str, with_stride: Optional[bool] = None):
    """-> (net_type, node_names, layer_infos, epoch, weights,
    input_shape) where layer_infos is [{type_id, type_name, primary,
    name, nin, nout}], weights is {layer_name: {tag: np.ndarray}} in
    the reference's native layouts, and input_shape is the NetParam's
    (C, H, W).

    ``with_stride`` selects the mshadow revision (it affects BOTH the
    ``Shape<3> input_shape`` embedded in the NetParam header and every
    tensor's SaveBinary shape); ``None`` auto-detects by attempting a
    complete parse under each hypothesis — a wrong hypothesis
    misaligns the stream and fails loudly (shape/consumption checks).
    """
    blob = open(path, "rb").read()
    if with_stride is not None:
        return _parse_file(path, blob, with_stride)
    try:
        return _parse_file(path, blob, with_stride=False)
    except ValueError:
        return _parse_file(path, blob, with_stride=True)


def _parse_file(path: str, blob: bytes, with_stride: bool):
    r = Reader(blob)
    net_type = r.i32()
    num_nodes, num_layers = r.i32(), r.i32()
    # NetParam.input_shape: mshadow::Shape<3> written inline with the
    # struct (nnet_config.h:34, SaveNet nnet_config.h:127) — 3 index_t
    # dims, +1 trailing stride_ word under the old-mshadow revision
    input_shape = r.u32s(3)
    if with_stride:
        r.u32s(1)
    init_end, extra_data_num = r.i32(), r.i32()
    r.raw(31 * 4)  # NetParam.reserved
    if not (0 < num_nodes < 1 << 20 and 0 < num_layers < 1 << 20):
        raise ValueError(f"{path}: not a reference cxxnet model "
                         f"(nodes={num_nodes}, layers={num_layers})")
    if init_end not in (0, 1) or not 0 <= extra_data_num < 1 << 10:
        raise ValueError(
            f"{path}: implausible NetParam (init_end={init_end}, "
            f"extra_data_num={extra_data_num}) — wrong Shape encoding?"
        )
    if extra_data_num:
        r.vec_i32()
    node_names = [r.string().decode() for _ in range(num_nodes)]
    infos = []
    for _ in range(num_layers):
        tid = r.i32()
        primary = r.i32()
        name = r.string().decode()
        nin = r.vec_i32()
        nout = r.vec_i32()
        base = tid - PAIRTEST_GAP if tid >= PAIRTEST_GAP else tid
        if base not in LAYER_TYPES:
            raise ValueError(f"unknown reference LayerType {tid}")
        infos.append({
            "type_id": tid, "type_name": LAYER_TYPES[base],
            "primary": primary, "name": name, "nin": nin, "nout": nout,
        })
    epoch = r.i64()
    model_blob = r.string()
    weights = _parse_blob(model_blob, infos, with_stride)
    return (net_type, node_names, infos, epoch, weights,
            tuple(int(d) for d in input_shape))


def _parse_blob(blob: bytes, infos, with_stride: bool):
    r = Reader(blob)
    weights: Dict[str, Dict[str, np.ndarray]] = {}
    for li, info in enumerate(infos):
        t = info["type_name"]
        if info["type_id"] >= PAIRTEST_GAP:
            raise ValueError("pairtest checkpoints are not importable "
                             "(debug-only composition)")
        key = info["name"] or f"layer{li}"
        if t == "fullc":
            p = _read_layer_param(r)
            w = _read_tensor(r, 2, with_stride,
                             (p["num_hidden"], p["num_input_node"]))
            b = _read_tensor(r, 1, with_stride, (p["num_hidden"],))
            weights[key] = {"wmat": w, "bias": b, "_param": p}
        elif t == "conv":
            p = _read_layer_param(r)
            g = max(1, p["num_group"])
            cout_g = p["num_channel"] // g
            w = _read_tensor(r, 3, with_stride)
            if w.shape[0] != g or w.shape[1] != cout_g:
                raise ValueError(
                    f"conv {key}: wmat shape {w.shape} inconsistent with "
                    f"LayerParam (g={g}, cout_g={cout_g})"
                )
            b = _read_tensor(r, 1, with_stride, (p["num_channel"],))
            weights[key] = {"wmat": w, "bias": b, "_param": p}
        elif t == "bias":
            p = _read_layer_param(r)
            weights[key] = {
                "bias": _read_tensor(r, 1, with_stride), "_param": p}
        elif t == "batch_norm":
            s = _read_tensor(r, 1, with_stride)
            b = _read_tensor(r, 1, with_stride, tuple(s.shape))
            weights[key] = {"wmat": s, "bias": b}
        elif t == "prelu":
            weights[key] = {"bias": _read_tensor(r, 1, with_stride)}
        # every other type saves nothing (layer.h:273 default)
    if not r.done():
        raise ValueError(
            f"model blob has {len(blob) - r.at} unconsumed bytes — "
            "wrong Shape encoding or unsupported layer payload"
        )
    return weights


def install(tr, infos, weights) -> int:
    """Install parsed reference weights into a conf-built NetTrainer,
    checking the binary's graph against the conf's."""
    g = tr.graph
    ref_named = {i["name"]: i for i in infos if i["name"]}
    n_set = 0
    for i, spec in enumerate(g.layers):
        if not spec.name or spec.name not in ref_named:
            continue
        info = ref_named[spec.name]
        if info["type_name"] != spec.type_name and spec.type_name != "shared":
            raise ValueError(
                f"layer {spec.name}: conf says {spec.type_name}, "
                f"reference model says {info['type_name']}"
            )
        w = weights.get(spec.name)
        if not w:
            continue
        if spec.type_name == "conv":
            p = w["_param"]
            gg = max(1, p["num_group"])
            # (g, cout_g, cin_g*kh*kw) -> the visitor's (cout, cin_g*kh*kw)
            tr.set_weight(w["wmat"].reshape(gg * w["wmat"].shape[1], -1),
                          spec.name, "wmat")
            if not p["no_bias"]:
                tr.set_weight(w["bias"], spec.name, "bias")
        else:
            for tag in ("wmat", "bias"):
                if tag in w:
                    tr.set_weight(w[tag], spec.name, tag)
        n_set += 1
    if n_set == 0:
        raise ValueError(
            "no layer of the conf matched a weighted layer in the "
            "reference model — check that conf and model belong together"
        )
    return n_set


def main() -> None:
    argv = sys.argv[1:]
    export = "--export" in argv
    stride = "--stride" in argv
    argv = [a for a in argv if a not in ("--export", "--stride")]
    if len(argv) != 3:
        raise SystemExit(
            "usage: python tools/import_ref_model.py "
            "<conf> <ref.model> <out.model>\n"
            "       python tools/import_ref_model.py --export [--stride] "
            "<conf> <native.model> <out_ref.model>\n"
            "(--stride: write the mshadow Shape-with-stride encoding for "
            "reference builds against that mshadow revision)"
        )
    conf_path, ref_path, out_path = argv
    from cxxnet_tpu import config as cfgmod
    from cxxnet_tpu.nnet.trainer import NetTrainer

    if export:
        entries = cfgmod.parse_file(conf_path)
        tr = NetTrainer()
        tr.set_params(cfgmod.split_sections(entries).global_entries)
        tr.init_model()
        tr.load_model(ref_path)
        n = export_ref_model(tr, out_path, with_stride=stride)
        print(f"exported {n} weighted layers -> {out_path} "
              "(reference binary format"
              f"{', stride Shape encoding' if stride else ''})")
        return

    net_type, _nodes, infos, epoch, weights, ishape = parse_ref_model(ref_path)
    print(f"reference model: net_type={net_type}, {len(infos)} layers, "
          f"{len(weights)} weighted, epoch_counter={epoch}, "
          f"input_shape={ishape}")
    entries = cfgmod.parse_file(conf_path)
    sections = cfgmod.split_sections(entries)
    tr = NetTrainer()
    tr.set_params(sections.global_entries)
    tr.init_model()
    n = install(tr, infos, weights)
    # carry the training position: the reference's updaters key their
    # LR schedules off epoch_counter, so a resumed/finetuned run must
    # not restart from step 0
    tr.epoch_counter = int(epoch)
    tr.save_model(out_path)
    print(f"installed {n} weighted layers -> {out_path}")




# --- exporter: native checkpoint -> reference binary format -------------

TYPE_IDS = {v: k for k, v in LAYER_TYPES.items()}


def _pack_str(b: bytes) -> bytes:
    return struct.pack("<Q", len(b)) + b


def _pack_vec_i32(v) -> bytes:
    return struct.pack("<Q", len(v)) + struct.pack(f"<{len(v)}i", *v)


# convolution_layer-inl.hpp InitTemp: nstep_ derives from
# temp_col_max/colunit; the reference default keeps convs chunked —
# exporting 0 would silently force nstep_=1 (one sample at a time)
REF_TEMP_COL_MAX = 64 << 18  # param.h default


def _pack_layer_param(**kw) -> bytes:
    full = [0] * 82  # param.h field order; float init fields stay zero
    full[0] = kw.get("num_hidden", 0)
    full[5] = kw.get("num_channel", 0)
    full[7] = kw.get("num_group", 1)
    full[8] = kw.get("kernel_height", 0)
    full[9] = kw.get("kernel_width", 0)
    full[10] = kw.get("stride", 1)
    full[11] = kw.get("pad_y", 0)
    full[12] = kw.get("pad_x", 0)
    full[13] = kw.get("no_bias", 0)
    full[14] = kw.get("temp_col_max", REF_TEMP_COL_MAX)
    full[16] = kw.get("num_input_channel", 0)
    full[17] = kw.get("num_input_node", 0)
    return struct.pack("<82i", *full)


def _pack_tensor(arr: np.ndarray, with_stride: bool = False) -> bytes:
    """mshadow SaveBinary.  ``with_stride`` must match the mshadow
    revision of the consuming reference build: shape-only (default) or
    the revision whose ``Shape<dim>`` carries a trailing ``stride_``
    (pass ``--stride`` at the CLI) — a mismatch shifts every subsequent
    read on the reference side."""
    out = struct.pack(f"<{arr.ndim}I", *arr.shape)
    if with_stride:
        out += struct.pack("<I", arr.shape[-1])  # contiguous rows
    return out + np.ascontiguousarray(arr, "<f4").tobytes()


def export_ref_model(tr, path: str, net_type: int = 0,
                     with_stride: bool = False) -> int:
    """Write a conf-built (or checkpoint-loaded) trainer's graph +
    weights in the reference's binary .model layout; returns the count
    of weighted layers written.  The inverse of :func:`install` —
    weights come back out through the same 2-D visitor views they went
    in by.  Weights and structure are exact; LayerParam init/temp
    fields are regenerated (init values only matter before training)."""
    g = tr.graph
    blob: list = []
    n_weighted = 0
    infos: list = []

    def tensor(arr):
        blob.append(_pack_tensor(arr, with_stride))

    for i, spec in enumerate(g.layers):
        t = spec.type_name
        if t == "shared":
            # reference encoding: kSharedLayer with primary index
            tid, primary = 0, spec.primary
        elif t in TYPE_IDS:
            tid, primary = TYPE_IDS[t], -1
        else:
            raise ValueError(
                f"layer {spec.name or i} ({t}) has no reference LayerType "
                "- the net is outside the reference's format"
            )
        infos.append(struct.pack("<ii", tid, primary))
        infos.append(_pack_str(spec.name.encode()))
        infos.append(_pack_vec_i32(spec.nindex_in))
        infos.append(_pack_vec_i32(spec.nindex_out))
        if t not in ("fullc", "conv", "bias", "batch_norm", "prelu"):
            continue
        lay = tr.net.layer_objs[i]
        w2 = tr.get_weight(spec.name, "wmat")
        b2 = tr.get_weight(spec.name, "bias")
        if t == "fullc":
            blob.append(_pack_layer_param(num_hidden=w2.shape[0],
                                          num_input_node=w2.shape[1]))
            tensor(w2)
            tensor(b2.reshape(-1))
        elif t == "conv":
            p = lay.param
            gg = max(1, p.num_group)
            cout = p.num_channel
            blob.append(_pack_layer_param(
                num_channel=cout, num_group=gg,
                kernel_height=p.kernel_height, kernel_width=p.kernel_width,
                stride=p.stride, pad_y=p.pad_y, pad_x=p.pad_x,
                no_bias=p.no_bias, num_input_channel=p.num_input_channel,
            ))
            tensor(w2.reshape(gg, cout // gg, -1))
            tensor(b2.reshape(-1) if b2.size
                   else np.zeros((cout,), np.float32))
        elif t == "bias":
            blob.append(_pack_layer_param(num_channel=b2.size))
            tensor(b2.reshape(-1))
        elif t == "batch_norm":
            tensor(w2.reshape(-1))
            tensor(b2.reshape(-1))
        elif t == "prelu":
            tensor(b2.reshape(-1))
        n_weighted += 1
    extra_num = getattr(g, "extra_data_num", 0)
    # NetParam.input_shape (C,H,W — nnet_config.h:252 Shape3(z,y,x),
    # consumed as s[0]=C by InitNet, neural_net-inl.hpp:218-220)
    ishape = tuple(int(d) for d in getattr(g, "input_shape", (0, 0, 0)))
    out = [struct.pack("<i", net_type),
           struct.pack("<2i", g.num_nodes, len(g.layers)),
           struct.pack("<3I", *ishape)]
    if with_stride:
        out.append(struct.pack("<I", ishape[-1]))  # Shape<3>::stride_
    out.append(struct.pack("<2i", 1, extra_num))
    out.append(b"\0" * (31 * 4))
    if extra_num:
        # reference extra_shape: flattened c,h,w per extra input
        flat = [d for shp in g.extra_shape for d in shp]
        out.append(_pack_vec_i32(flat))
    for name in g.node_names:
        out.append(_pack_str(name.encode()))
    out.extend(infos)
    out.append(struct.pack("<q", int(tr.epoch_counter)))
    out.append(_pack_str(b"".join(blob)))
    with open(path, "wb") as f:
        f.write(b"".join(out))
    return n_weighted


if __name__ == "__main__":
    main()
