"""SDC=1 lane: bit-flip detect/quarantine with bitwise parity + canary.

The integrity-plane acceptance (doc/robustness.md "Integrity plane"),
proven end to end through the real CLI on a 4-process CPU mesh:

* **Run A (flip)** — 4 ``jax.distributed`` processes train the
  MNIST-format MLP conf with ``integrity_every = 1``.  Rank 3 is armed
  with ``fault_inject=device.state:bitflip:1:1``: one real bit of one
  live parameter tensor flips on that rank at its first
  ``start_round``.  The fingerprint vote must detect it within
  ``integrity_every`` rounds, name rank 3, quarantine it (exit code
  41), and the survivors must evict + rebuild **in-process** and
  resume from the last consensus (fingerprint-verified) checkpoint.
* **Run B (clean)** — the surviving schedule executed deliberately: a
  3-process run that never contained the corrupt rank (the flip lands
  in run A's first round, which the quarantine discards and re-runs on
  the survivors from the seeded init checkpoint).
* **Parity** — every checkpoint manifest CRC32 the two runs write must
  be IDENTICAL: a run that absorbed and excised real silent data
  corruption ends bit-equal to one where the bad replica never
  existed.
* **Serve canary** — an engine over run B's checkpoints
  (``integrity_probe = 1``) records its golden, survives a clean
  sweep, degrades ``/healthz`` with ``integrity_failed`` on an
  injected CRC drift, and readmits itself on the next clean score.
* **Overhead** — a single-process run of the same conf measures the
  fingerprint sweep against the round wall clock; the ratio must stay
  ≤ 2% and lands in the ``perf_guard`` history (``--bench
  integrity_bench``) with the detection latency so both are
  regression-tracked.

Usage::

    python tools/sdc_smoke.py --out /tmp/_sdc            # the CI lane
    python tools/perf_guard.py --bench integrity_bench \\
        --input /tmp/_sdc/sdc.json --history bench_history.jsonl

Exit code: 0 when detection, quarantine, parity, canary, and the
overhead bound all hold; 1 otherwise (hard gate, not weather).
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

NUM_ROUND = 6
GLOBAL_BATCH = 12          # divides 4-way AND 3-way data meshes
N_IMAGES = 960             # 80 global batches/round; blocks tile 4 and 3
N_HIDDEN = 256
FLIP_RANK = 3              # never rank 0 (it hosts both coordinators)
# Seed chosen so the deterministic payload stream picks a NONZERO
# weight (l0_fc1/wmat, mantissa bit 12 — a ~0.05% relative
# perturbation).  A flip that lands on an exactly-zero element at a
# denormal-scale bit is absorbed by the next update's rounding (the
# difference is below one ulp of the updated value) and leaves no
# corruption to detect — mathematically benign, not a missed verdict.
FAULT_SEED = 4
OVERHEAD_MAX = 0.02        # fingerprint sweep / round wall bound
QUARANTINE_RC = 41


def _free_port() -> int:
    from cxxnet_tpu.parallel.elastic import free_port

    return free_port()


def make_data(out_dir: str) -> None:
    import numpy as np

    from cxxnet_tpu.io.mnist import write_idx_images, write_idx_labels

    rng = np.random.RandomState(0)
    imgs = rng.randint(0, 256, (N_IMAGES, 4, 4)).astype(np.uint8)
    labels = (imgs.reshape(N_IMAGES, -1).mean(1) > 127).astype(np.uint8)
    write_idx_images(os.path.join(out_dir, "img.idx"), imgs)
    write_idx_labels(os.path.join(out_dir, "lab.idx"), labels)


def netconfig(hidden: int = N_HIDDEN, dev: str = "cpu") -> str:
    return f"""netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = {hidden}
  init_sigma = 0.1
layer[fc1->out] = fullc:fc2
  nhidden = 2
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,16
batch_size = {GLOBAL_BATCH}
dev = {dev}
"""


NETCONFIG = netconfig()


def make_conf(out_dir: str, hidden: int = N_HIDDEN,
              dev: str = "cpu") -> str:
    """One conf for every process of both runs; per-run/per-rank keys
    ride as CLI overrides."""
    conf = os.path.join(out_dir, "sdc.conf")
    with open(conf, "w", encoding="utf-8") as f:
        f.write(f"""
data = train
iter = mnist
  path_img = "{out_dir}/img.idx"
  path_label = "{out_dir}/lab.idx"
  shuffle = 1
  dist_shard = block
iter = end
{netconfig(hidden, dev)}num_round = {NUM_ROUND}
eval_train = 0
eta = 0.1
momentum = 0.9
seed = 7
save_ustate = 1
det_reduce = 1
metric = error
silent = 1
telemetry = 1
integrity_every = 1
integrity_probe = 1
elastic = 1
elastic_min_replicas = 2
elastic_heartbeat_s = 0.25
elastic_timeout_s = 3
collective_timeout_s = 30
""")
    return conf


def launch_rank(conf: str, workdir: str, model_dir: str, rank: int,
                nproc: int, jax_port: int, elastic_port: int, extra=(),
                platform: str = "cpu"):
    d = os.path.join(workdir, f"p{rank}")
    os.makedirs(d, exist_ok=True)
    env = {
        **os.environ,
        "PYTHONPATH": REPO,
        "JAX_PLATFORMS": platform,
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
    }
    over = [f"model_dir={model_dir}"]
    if elastic_port:
        over.append(f"elastic_coordinator=localhost:{elastic_port}")
    if rank >= 0 and nproc > 1:
        over += [f"dist_coordinator=localhost:{jax_port}",
                 f"dist_num_proc={nproc}", f"dist_proc_id={rank}"]
    over += list(extra)
    log = open(os.path.join(d, "out.log"), "wb")
    p = subprocess.Popen(
        [sys.executable, "-u", "-m", "cxxnet_tpu", conf] + over,
        env=env, cwd=d, stdout=log, stderr=subprocess.STDOUT,
    )
    p._log_file = log  # type: ignore[attr-defined]
    p._workdir = workdir  # type: ignore[attr-defined]
    p._rank = rank     # type: ignore[attr-defined]
    return p


def rank_log(workdir: str, rank: int) -> str:
    try:
        with open(os.path.join(workdir, f"p{rank}", "out.log"), "r",
                  encoding="utf-8", errors="replace") as f:
            return f.read()
    except OSError:
        return ""


def drain(procs, timeout: float, problems, tag: str,
          expect_fail_ranks=()):
    deadline = time.time() + timeout
    for p in procs:
        left = max(1.0, deadline - time.time())
        try:
            p.wait(timeout=left)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()
            problems.append(f"{tag}: rank {p._rank} process timed out")
        finally:
            p._log_file.close()
    for p in procs:
        if p._rank in expect_fail_ranks:
            continue
        if p.returncode != 0:
            problems.append(
                f"{tag}: rank {p._rank} exited rc={p.returncode}; "
                "tail:\n" + rank_log(p._workdir, p._rank)[-2500:])


def read_crcs(model_dir: str) -> dict:
    from cxxnet_tpu.utils import checkpoint as ckpt

    out = {}
    for round_, path in ckpt.list_checkpoints(model_dir):
        man = ckpt.read_manifest(path)
        if man is not None:
            out[round_] = man["crc32"]
    return out


def read_telemetry(workdir: str, rank: int = 0) -> list:
    path = os.path.join(workdir, f"p{rank}", "telemetry.jsonl")
    recs = []
    try:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line:
                    recs.append(json.loads(line))
    except (OSError, ValueError):
        pass
    return recs


def run_flip(conf: str, workdir: str, model_dir: str,
             timeout: float, problems) -> dict:
    """Run A: 4 ranks; rank 3 flips one real bit at its first
    start_round.  Detection -> exit-41 quarantine -> in-process evict +
    rebuild -> consensus rollback, all inside one CLI invocation."""
    os.makedirs(model_dir, exist_ok=True)
    jax_port, elastic_port = _free_port(), _free_port()
    procs = []
    for r in range(4):
        extra = ()
        if r == FLIP_RANK:
            # ordered stream: fault_seed must precede the spec it
            # seeds (faults.configure contract)
            extra = (f"fault_seed={FAULT_SEED}",
                     "fault_inject=device.state:bitflip:1:1")
        procs.append(launch_rank(conf, workdir, model_dir, r, 4,
                                 jax_port, elastic_port, extra=extra))
    drain(procs, timeout, problems, "flip",
          expect_fail_ranks={FLIP_RANK})
    if procs[FLIP_RANK].returncode != QUARANTINE_RC:
        problems.append(
            f"flip: rank {FLIP_RANK} exited "
            f"rc={procs[FLIP_RANK].returncode}, expected the "
            f"quarantine code {QUARANTINE_RC}; tail:\n"
            + rank_log(workdir, FLIP_RANK)[-2500:])
    flip_log = rank_log(workdir, FLIP_RANK)
    if "self-quarantining (exit 41)" not in flip_log:
        problems.append("flip: the corrupt rank never announced its "
                        "quarantine; tail:\n" + flip_log[-2000:])
    log0 = rank_log(workdir, 0)
    detect = [int(m) for m in re.findall(
        r"INTEGRITY: integrity state check failed at round (\d+)", log0)]
    named = re.findall(r"corrupt rank (\d+)", log0)
    if not detect:
        problems.append("flip: rank 0 never reported the state verdict; "
                        "log tail:\n" + log0[-2500:])
    if not named or int(named[0]) != FLIP_RANK:
        problems.append(f"flip: vote named rank {named[:1]}, expected "
                        f"{FLIP_RANK}")
    resume = [int(m) for m in re.findall(
        r"integrity_evict -> rebuilding.*?\n.*?resuming at round (\d+)",
        log0, re.S)]
    if not resume:
        problems.append("flip: survivors never rebuilt after the evict; "
                        "log tail:\n" + log0[-2500:])
    tele = read_telemetry(workdir)
    rebuild_s = max((r.get("elastic", {}).get("last_rebuild_s", 0.0)
                     for r in tele), default=0.0)
    return {
        "detect_round": detect[0] if detect else None,
        "resume_round": resume[0] if resume else None,
        "rebuild_wall_s": rebuild_s,
    }


def run_clean(conf: str, workdir: str, model_dir: str,
              timeout: float, problems) -> None:
    """Run B: the corrupt rank's schedule, minus the corrupt rank.

    The flip lands in run A's FIRST round, so the quarantine discards
    that round entirely and re-runs the whole schedule on the 3
    survivors from the (seeded, mesh-independent) init checkpoint.
    The bitwise-parity partner is therefore a 3-process run that never
    contained rank 3 at all — a strictly stronger claim than replaying
    a planned shrink: a run that absorbed and excised real corruption
    is indistinguishable from one where the bad replica never existed."""
    os.makedirs(model_dir, exist_ok=True)
    jax_port, elastic_port = _free_port(), _free_port()
    procs = [launch_rank(conf, workdir, model_dir, r, 3, jax_port,
                         elastic_port)
             for r in range(3)]
    drain(procs, timeout, problems, "clean")


def run_overhead(conf: str, workdir: str, model_dir: str,
                 timeout: float, problems, platform: str = "cpu") -> dict:
    """Single-process run of the same conf: the fingerprint sweep's
    share of the round wall clock, warmup round excluded."""
    os.makedirs(model_dir, exist_ok=True)
    p = launch_rank(conf, workdir, model_dir, 0, 1, 0, 0,
                    extra=["elastic=0"], platform=platform)
    drain([p], timeout, problems, "overhead")
    tele = read_telemetry(workdir)
    ratios = []
    for rec in tele:
        integ = rec.get("integrity", {})
        step = rec.get("step", {})
        wall = step.get("steps", 0) * step.get("mean_ms", 0) / 1e3
        # the FIRST sweep (checks == 1) carries the digest-program
        # compiles; steady state starts at the second check
        if wall > 0 and integ.get("checks", 0) >= 2:
            ratios.append(integ.get("last_elapsed_s", 0.0) / wall)
    if not ratios:
        problems.append("overhead: no usable telemetry records")
        return {"overhead_ratio": None}
    ratio = sum(ratios) / len(ratios)
    if ratio > OVERHEAD_MAX:
        problems.append(
            f"overhead: fingerprint sweep is {ratio:.2%} of round wall "
            f"(bound {OVERHEAD_MAX:.0%})")
    return {"overhead_ratio": round(ratio, 5),
            "rounds_measured": len(ratios)}


def run_serve_canary(model_dir: str, problems) -> dict:
    """Engine over the clean run's checkpoints: golden recorded at
    load, clean sweep, injected drift -> degraded healthz with the
    integrity_failed token, next clean sweep readmits."""
    from cxxnet_tpu import serve

    cfg = NETCONFIG + "integrity_probe = 1\n"
    eng = serve.Engine(cfg=cfg, model_dir=model_dir, max_batch_size=8,
                       batch_timeout_ms=0, silent=True)
    out = {"canary_golden_src": None, "canary_detected": False,
           "canary_readmitted": False}
    try:
        snap = eng.snapshot_stats().get("integrity", {})
        out["canary_golden_src"] = snap.get("golden_src")
        if snap.get("golden_crc32") is None:
            problems.append("canary: engine recorded no golden")
            return out
        if not eng.check_canary():
            problems.append("canary: clean sweep failed (false alarm)")
        eng.inject_canary_mismatch = 1
        if eng.check_canary():
            problems.append("canary: injected drift went undetected")
        h = eng.healthz()
        detected = (h["status"] == "degraded"
                    and "integrity_failed" in h.get("reasons", ()))
        out["canary_detected"] = detected
        if not detected:
            problems.append(f"canary: healthz did not degrade: {h}")
        clean = eng.check_canary()
        ok = eng.healthz()["status"] == "ok"
        out["canary_readmitted"] = clean and ok
        if not (clean and ok):
            problems.append("canary: latch did not clear on the clean "
                            "sweep")
    finally:
        eng.close()
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="/tmp/_sdc",
                    help="scratch + verdict directory")
    ap.add_argument("--timeout", type=float, default=420.0,
                    help="per-run wall-clock budget (seconds)")
    ap.add_argument("--json", dest="json_path", default="",
                    help="verdict path (default <out>/sdc.json)")
    ap.add_argument("--overhead-only", action="store_true",
                    help="skip the flip/parity/canary walk and measure "
                         "only the fingerprint-sweep overhead (the "
                         "tpu_queue full-size bench entry)")
    ap.add_argument("--dev", default="cpu",
                    help="conf dev= value for the overhead run "
                         "(e.g. tpu)")
    ap.add_argument("--hidden", type=int, default=N_HIDDEN,
                    help="fc1 width for the overhead run (scale the "
                         "model up for the on-chip measurement)")
    args = ap.parse_args()

    os.makedirs(args.out, exist_ok=True)
    make_data(args.out)
    conf = make_conf(args.out, hidden=args.hidden, dev=args.dev)
    problems: list = []
    platform = "tpu" if args.dev.startswith("tpu") else "cpu"

    if args.overhead_only:
        over_dir = os.path.join(args.out, "overhead")
        overhead = run_overhead(conf, over_dir,
                                os.path.join(over_dir, "models"),
                                args.timeout, problems,
                                platform=platform)
        doc = {
            "bench": "integrity_bench",
            "ts": time.time(),
            "rounds": NUM_ROUND,
            "global_batch": GLOBAL_BATCH,
            "hidden": args.hidden,
            "dev": args.dev,
            **overhead,
            "problems": problems,
            "verdict": "ok" if not problems else "fail",
        }
        json_path = args.json_path or os.path.join(args.out, "sdc.json")
        with open(json_path, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1)
        print(json.dumps(doc, indent=1))
        for p in problems:
            print(f"FAIL {p}", file=sys.stderr)
        return 1 if problems else 0

    t0 = time.time()
    flip_dir = os.path.join(args.out, "flip")
    flip = run_flip(conf, flip_dir, os.path.join(flip_dir, "models"),
                    args.timeout, problems)
    flip_s = time.time() - t0

    detect_rounds = None
    if flip["detect_round"] is not None:
        # the flip lands at the corrupt rank's FIRST start_round
        # (round 0); with integrity_every = 1 the verdict must land at
        # that round's boundary check
        detect_rounds = flip["detect_round"] + 1
        if detect_rounds > 1:
            problems.append(
                f"flip: detection took {detect_rounds} rounds with "
                "integrity_every = 1")

    crc_equal = False
    flip_crcs: dict = {}
    clean_crcs: dict = {}
    clean_s = 0.0
    if flip["resume_round"] is not None and not problems:
        t1 = time.time()
        clean_dir = os.path.join(args.out, "clean")
        run_clean(conf, clean_dir, os.path.join(clean_dir, "models"),
                  timeout=args.timeout, problems=problems)
        clean_s = time.time() - t1
        flip_crcs = read_crcs(os.path.join(flip_dir, "models"))
        clean_crcs = read_crcs(os.path.join(clean_dir, "models"))
        if len(flip_crcs) != NUM_ROUND + 1:
            problems.append(
                f"flip run wrote rounds {sorted(flip_crcs)}, expected "
                f"{NUM_ROUND + 1} checkpoints")
        crc_equal = bool(flip_crcs) and flip_crcs == clean_crcs
        if not crc_equal:
            problems.append(
                "BITWISE PARITY FAILED: flipped-and-quarantined CRCs "
                f"{ {k: hex(v) for k, v in sorted(flip_crcs.items())} } "
                "!= clean-schedule CRCs "
                f"{ {k: hex(v) for k, v in sorted(clean_crcs.items())} }")

    canary = {"canary_golden_src": None}
    if not problems:
        canary = run_serve_canary(
            os.path.join(args.out, "clean", "models"), problems)

    over_dir = os.path.join(args.out, "overhead")
    overhead = run_overhead(conf, over_dir,
                            os.path.join(over_dir, "models"),
                            args.timeout, problems)

    doc = {
        "bench": "integrity_bench",
        "ts": time.time(),
        "rounds": NUM_ROUND,
        "global_batch": GLOBAL_BATCH,
        "detect_rounds": detect_rounds,
        "resume_round": flip["resume_round"],
        "rebuild_wall_s": flip["rebuild_wall_s"],
        "crc_equal": crc_equal,
        "crcs": {str(k): f"{v:#010x}"
                 for k, v in sorted(flip_crcs.items())},
        **canary,
        **overhead,
        "flip_wall_sec": round(flip_s, 3),
        "clean_wall_sec": round(clean_s, 3),
        "problems": problems,
        "verdict": "ok" if not problems else "fail",
    }
    json_path = args.json_path or os.path.join(args.out, "sdc.json")
    with open(json_path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1)
    print(json.dumps(doc, indent=1))
    for p in problems:
        print(f"FAIL {p}", file=sys.stderr)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
