"""Shared scaffold for the step-time bisection tools.

One place for what used to be three verbatim copies (googlenet/resnet/
vgg): the persistent-cache config, the bench-harness timing loop, and —
critically — the same fail-fast discipline as ``bench.py`` itself
(relay probe before jax init, watchdog thread), so a mid-queue relay
death produces a stage-named diagnostic in seconds instead of burning
the entry's full timeout budget at 0% CPU (the round-3 rc=124 mode).
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

CACHE_DIR = os.path.join(REPO, ".jax_cache")


def run_bisect(variant_conf, default_names, batch: int = 128,
               scan_k: int = 30) -> None:
    """Probe/arm, configure the cache, and time each requested variant
    with the bench harness (so bisect numbers stay comparable to
    ``bench.py`` numbers)."""
    import bench

    if bench._tpu_expected():
        if not bench._probe_relay():
            bench._emit_error(
                "relay dead: refusing to dial the TPU tunnel from a "
                "bisect tool"
            )
            raise SystemExit(0)
        if not bench._acquire_tpu_lock():
            bench._emit_error(
                "another TPU client holds the relay lock; refusing to "
                "double-dial from a bisect tool"
            )
            raise SystemExit(0)
    names = sys.argv[1:] or default_names
    # arm for startup (jax import + cache config), then RE-arm one
    # single-run deadline at each variant: any single hang fires within
    # WATCHDOG_SEC — inside tpu_queue.sh's external `timeout` budget —
    # while a healthy multi-variant sweep is never killed by the
    # single-run default.  (One deadline scaled by len(names) could
    # exceed the external budget and reproduce the rc=124 mode.)
    bench._arm_watchdog(bench.WATCHDOG_SEC)
    try:
        import jax

        os.makedirs(CACHE_DIR, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", CACHE_DIR)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

        from bench import _bench_imagenet_conf

        for name in names:
            wd = bench._STAGE.get("watchdog")
            if wd is not None:
                wd.cancel()
            bench._arm_watchdog(bench.WATCHDOG_SEC)
            bench._set_stage(f"bisect:{name}")
            _bench_imagenet_conf(
                f"bisect:{name}", name, variant_conf(name, batch),
                batch, scan_k,
            )
    finally:
        bench._STAGE["done"] = True
        wd = bench._STAGE.get("watchdog")
        if wd is not None:
            wd.cancel()
