"""Local perf-regression sentinel over io_bench / serve_bench results.

Rounds 3-5's TPU bench artifacts were lost to relay outages because
bench results lived in ad-hoc JSON files nobody appended to.  This tool
makes bench artifacts first-class and loss-proof:

* **history** — every run is appended to a committed-format JSONL file
  (one ``{"ts", "bench", "host", "metrics": {...}}`` object per line;
  the file is meant to be committed next to the code it measures, so a
  lost relay session costs one entry, not the whole series);
* **rolling baseline** — each metric is compared against the median of
  the last ``--window`` (default 5) prior entries of the same bench;
* **noise band** — a metric only counts as a regression/improvement
  when it leaves the ``--band`` (default 20%) envelope around the
  baseline, orientation-aware: ``*_per_sec``-style metrics regress
  downward, ``p50/p95/p99``/``*_ms``-style metrics regress upward;
* **verdict** — one schema-stable JSON document on stdout (and
  ``--json``): ``verdict`` is ``baseline`` (not enough history), ``ok``
  or ``regression``; regressions also emit an ``alert.perf_regression``
  structured event (``--event-log`` to persist it) and bump
  ``perf_regressions_total{bench}``.

Usage::

    python tools/io_bench.py --json /tmp/io.json
    python tools/perf_guard.py --bench io_bench --input /tmp/io.json \\
        --history bench_history.jsonl
    python tools/serve_bench.py > /tmp/serve.json
    python tools/perf_guard.py --bench serve_bench --input /tmp/serve.json
    python tools/perf_guard.py --smoke        # the OBS=1 CI lane

Exit code: 0 on ``ok``/``baseline``; 1 on schema problems, or on
``regression`` when ``--strict`` is given (CI lanes stay green on slow
hardware days unless they opt in).
"""

from __future__ import annotations

import argparse
import json
import math
import os
import platform
import sys
import time
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

VERDICTS = ("baseline", "ok", "regression")

#: substrings marking a metric as lower-is-better (latencies, and the
#: mesh lane's compile counts — MORE compiles is the re-jit regression)
_LOWER_MARKERS = ("latency", "_ms", "p50", "p95", "p99", "wall_s",
                  "compiles", "programs", "rebuild_wall_s",
                  "restart_wall_s", "shed_ratio", "final_err",
                  "elapsed_s", "disk_bytes_final", "violations",
                  "overhead_ratio", "detect_rounds")


def lower_is_better(name: str) -> bool:
    # match against the FULL dotted name: a latency metric whose leaf
    # carries no marker (latency_ms.mean, latency_ms.max) must still
    # regress upward, not get its direction inverted
    return any(m in name for m in _LOWER_MARKERS)


# ----------------------------------------------------------------------
# flatteners: bench JSON documents -> {metric_name: float}
def _walk_numbers(prefix: str, obj, out: Dict[str, float]) -> None:
    if isinstance(obj, dict):
        for k, v in obj.items():
            _walk_numbers(f"{prefix}.{k}" if prefix else str(k), v, out)
    elif isinstance(obj, (int, float)) and not isinstance(obj, bool):
        if math.isfinite(obj):
            out[prefix] = float(obj)


def flatten_io_bench(doc: dict) -> Dict[str, float]:
    """Per-mode throughput rates from an ``io_bench --json`` report."""
    out: Dict[str, float] = {}
    for row in doc.get("results", []):
        mode = row.get("mode", "?")
        for key in ("img_per_sec", "decode_augment_per_sec"):
            v = row.get(key)
            if isinstance(v, (int, float)) and math.isfinite(v):
                out[f"{mode}.{key}"] = float(v)
    return out


def flatten_serve_bench(doc: dict) -> Dict[str, float]:
    """Throughput + latency percentiles from a serve_bench report."""
    out: Dict[str, float] = {}
    closed = doc.get("closed_loop", {})
    for leg in ("sequential", "concurrent"):
        d = closed.get(leg, {})
        for key in ("req_per_sec", "rows_per_sec"):
            v = d.get(key)
            if isinstance(v, (int, float)) and math.isfinite(v):
                out[f"closed.{leg}.{key}"] = float(v)
        _walk_numbers(f"closed.{leg}.latency_ms",
                      d.get("latency_ms", {}), out)
    v = closed.get("speedup")
    if isinstance(v, (int, float)) and math.isfinite(v):
        out["closed.speedup"] = float(v)
    _flatten_burst(doc.get("open_loop_burst", {}), out)
    return out


def _flatten_burst(burst: dict, out: Dict[str, float]) -> None:
    """The burst-profile series shared by the serve_bench and
    fleet_bench lanes: achieved rate, shed ratio (admission pressure),
    and the sustained latency percentiles."""
    v = burst.get("achieved_req_per_sec")
    if isinstance(v, (int, float)) and math.isfinite(v):
        out["burst.achieved_req_per_sec"] = float(v)
    sent, shed = burst.get("sent"), burst.get("shed")
    if (isinstance(sent, (int, float)) and sent
            and isinstance(shed, (int, float))):
        out["burst.shed_ratio"] = float(shed) / float(sent)
    _walk_numbers("burst.latency_ms", burst.get("latency_ms", {}), out)


def flatten_wire_bench(doc: dict) -> Dict[str, float]:
    """The WIRE lane's series (``serve_bench --wire-ab``): both wire
    formats' HTTP closed-loop throughput and latency, the binary/JSON
    speedup, and the bitwise score-parity bit (1.0 = equal).  A change
    that quietly erodes the zero-copy win — an extra decode copy, a
    lost keep-alive — drifts the speedup down here even while the hard
    >= 1.5x lane assertion still passes."""
    out: Dict[str, float] = {}
    ab = doc.get("wire_ab", {})
    for leg in ("json", "binary"):
        d = ab.get(leg, {})
        for key in ("req_per_sec", "rows_per_sec"):
            v = d.get(key)
            if isinstance(v, (int, float)) and math.isfinite(v):
                out[f"{leg}.{key}"] = float(v)
        _walk_numbers(f"{leg}.latency_ms", d.get("latency_ms", {}), out)
    v = ab.get("speedup")
    if isinstance(v, (int, float)) and math.isfinite(v):
        out["speedup"] = float(v)
    out["bitwise_equal_scores"] = float(
        bool(ab.get("bitwise_equal_scores")))
    _flatten_burst(doc.get("open_loop_burst", {}), out)
    return out


def flatten_mesh_parity(doc: dict) -> Dict[str, float]:
    """Wall time + compile/program counts from a ``tools/mesh_parity.py``
    verdict — the one-program claim as a banded series: a change that
    starts re-jitting per replica moves ``multi.compiles`` (orientation:
    lower is better) far outside the noise band, and the sentinel flags
    it even if the lane's exact-count assertions were ever loosened."""
    out: Dict[str, float] = {}
    for side in ("multi", "single"):
        d = doc.get(side, {})
        for key in ("wall_sec", "compiles", "programs"):
            v = d.get(key)
            if isinstance(v, (int, float)) and math.isfinite(v):
                out[f"{side}.{key}"] = float(v)
    return out


def flatten_quant_bench(doc: dict) -> Dict[str, float]:
    """The QUANT lane's series (``tools/quant_smoke.py`` /
    ``serve_bench --quant``): both legs' throughput and latency, the
    quant/f32 speedup, the weight-bytes ratio, and — when the document
    carries the export verdict — the gate's measured agreement.  A
    change that quietly shrinks the bytes win or the agreement drifts
    out of the band here even while the hard lane assertions pass."""
    out: Dict[str, float] = {}
    ab = doc.get("quant_ab", {})
    for leg in ("f32", "quant"):
        d = ab.get(leg, {})
        for key in ("req_per_sec", "rows_per_sec"):
            v = d.get(key)
            if isinstance(v, (int, float)) and math.isfinite(v):
                out[f"{leg}.{key}"] = float(v)
        _walk_numbers(f"{leg}.latency_ms", d.get("latency_ms", {}), out)
    for key in ("speedup", "bytes_ratio"):
        v = ab.get(key)
        if isinstance(v, (int, float)) and math.isfinite(v):
            out[key] = float(v)
    v = (doc.get("export") or {}).get("agreement")
    if isinstance(v, (int, float)) and math.isfinite(v):
        out["agreement"] = float(v)
    return out


def flatten_elastic(doc: dict) -> Dict[str, float]:
    """The ELASTIC lane's series (``tools/elastic_kill.py``): recovery
    cost as regression-tracked numbers — rebuild wall time (lower is
    better: a change that slows detection, teardown, or the consensus
    reload drifts it up), the recovered post-rebuild training rate, and
    the parity bit itself (crc_equal as 0/1 — a run that stops being
    bitwise equal collapses far outside any noise band)."""
    out: Dict[str, float] = {}
    out["crc_equal"] = 1.0 if doc.get("crc_equal") else 0.0
    for side in ("churn", "planned"):
        d = doc.get(side, {})
        for key in ("wall_sec", "rebuild_wall_s",
                    "recovered_samples_per_sec"):
            v = d.get(key)
            if isinstance(v, (int, float)) and math.isfinite(v):
                out[f"{side}.{key}"] = float(v)
    return out


def flatten_fleet_bench(doc: dict) -> Dict[str, float]:
    """The FLEET lane's series (``tools/fleet_smoke.py``): replica
    restart wall-clock (lower is better — a change that slows
    detection, backoff, or replica startup drifts it up), sustained
    p50/p99 under the burst profile, the achieved rate, and the shed
    ratio (admission pressure; a change that sheds much more under the
    same offered load leaves the band even while the hard zero-error
    assertions still pass)."""
    out: Dict[str, float] = {}
    v = doc.get("restart_wall_s")
    if isinstance(v, (int, float)) and math.isfinite(v):
        out["restart_wall_s"] = float(v)
    _flatten_burst(doc.get("burst", {}), out)
    return out


def flatten_async_bench(doc: dict) -> Dict[str, float]:
    """The ASYNC lane's series (``tools/async_ab.py``): the parity bit
    (crc_equal as 0/1 — a run that stops being bitwise equal collapses
    far outside any band), per-leg final error (lower is better — a
    staleness leg drifting from the sync baseline shows up here even
    inside the lane's --tol) and wall seconds, and the overlap
    micro-bench's step-wall/overlap-fraction pair (step_wall lower is
    better, overlap_fraction higher — a change that silently
    de-overlaps the dispatch pipeline drags the fraction down)."""
    out: Dict[str, float] = {}
    parity = doc.get("parity")
    if isinstance(parity, dict):
        out["parity.crc_equal"] = 1.0 if parity.get("crc_equal") else 0.0
        for key in ("sync_wall_sec", "async_wall_sec"):
            v = parity.get(key)
            if isinstance(v, (int, float)) and math.isfinite(v):
                out[f"parity.{key}"] = float(v)
    for name, leg in ((doc.get("ab") or {}).get("legs") or {}).items():
        if not isinstance(leg, dict):
            continue
        for key in ("final_err", "wall_sec", "overlap_fraction"):
            v = leg.get(key)
            if isinstance(v, (int, float)) and math.isfinite(v):
                out[f"ab.{name}.{key}"] = float(v)
    overlap = doc.get("overlap")
    if isinstance(overlap, dict):
        for key in ("sync_step_wall_sec", "async_step_wall_sec",
                    "overlap_fraction", "speedup"):
            v = overlap.get(key)
            if isinstance(v, (int, float)) and math.isfinite(v):
                out[f"overlap.{key}"] = float(v)
    return out


def flatten_tenant_bench(doc: dict) -> Dict[str, float]:
    """The TENANT lane's series (``tools/tenant_smoke.py``): per-tenant
    publish counts, the compaction yield (reclaimed shards/bytes — a
    change that silently stops compacting collapses these to zero far
    outside any band), the residual disk footprint after retention
    (lower is better: a retention bug shows up as the log growing
    again), the SLO overlay's engagement (alerts_fired/sheds must stay
    0 under the lane's light load), the crash-window CRC bit, and the
    end-to-end wall clock."""
    out: Dict[str, float] = {}
    for key in ("records", "compactions", "compacted_shards",
                "compacted_bytes", "alerts_fired", "sheds",
                "elapsed_s"):
        v = doc.get(key)
        if isinstance(v, (int, float)) and math.isfinite(v):
            out[key] = float(v)
    out["crc_ok_after_kill"] = (
        1.0 if doc.get("crc_ok_after_kill") else 0.0)
    for tname, n in (doc.get("published") or {}).items():
        if isinstance(n, (int, float)) and math.isfinite(n):
            out[f"published.{tname}"] = float(n)
    for tname, v in (doc.get("disk_bytes_final") or {}).items():
        if isinstance(v, (int, float)) and math.isfinite(v):
            out[f"disk_bytes_final.{tname}"] = float(v)
    return out


def flatten_integrity_bench(doc: dict) -> Dict[str, float]:
    """The SDC lane's series (``tools/sdc_smoke.py``): detection
    latency in rounds (lower is better — with ``integrity_every = 1``
    it must stay at 1; a cadence or vote regression drifts it up), the
    fingerprint sweep's share of the round wall clock (lower is
    better, bounded at 2% by the lane itself), the quarantine rebuild
    wall time, the bitwise-parity and canary bits as 0/1 (a run that
    stops being bit-equal, or a canary that stops detecting/
    readmitting, collapses far outside any noise band), and the
    end-to-end wall clocks."""
    out: Dict[str, float] = {}
    for key in ("detect_rounds", "overhead_ratio", "rebuild_wall_s",
                "flip_wall_sec", "clean_wall_sec"):
        v = doc.get(key)
        if isinstance(v, (int, float)) and math.isfinite(v):
            out[key] = float(v)
    for key in ("crc_equal", "canary_detected", "canary_readmitted"):
        out[key] = 1.0 if doc.get(key) else 0.0
    return out


def flatten_crash_audit(doc: dict) -> Dict[str, float]:
    """The CRASH lane's series (``tools/crash_audit.py``): coverage
    (states explored / distinct — a change that quietly shrinks the
    audited state space collapses these far outside any band),
    violations (lower is better; nonzero already hard-fails the lane,
    the series keeps the zero pinned in history), and the audit wall
    time."""
    out: Dict[str, float] = {}
    for key in ("states_explored", "distinct_states",
                "violations_count", "wall_s"):
        v = doc.get(key)
        if isinstance(v, (int, float)) and math.isfinite(v):
            out[key.replace("violations_count", "violations")] = float(v)
    return out


def flatten_elastic_crash(doc: dict) -> Dict[str, float]:
    """The elastic kill -9 crash-window series (``tools/elastic_kill.py
    --kill-checkpoint``): the torn-tmp sighting (1.0 means the SIGKILL
    really landed inside the atomic-publish window — losing it means the
    kill hook drifted off the race), the consensus round resumed from,
    restart latency (lower is better), the final CRC-valid round count,
    and the end-to-end wall clock."""
    out: Dict[str, float] = {}
    out["tmp_orphan"] = 1.0 if doc.get("tmp_orphan") else 0.0
    for key in ("resumed_from", "restart_wall_s", "rounds_final",
                "wall_sec"):
        v = doc.get(key)
        if isinstance(v, (int, float)) and math.isfinite(v):
            out[key] = float(v)
    return out


def flatten_kernel_bench(doc: dict) -> Dict[str, float]:
    """The KERNEL lane's series (``tools/kernel_ab.py``): per kernel,
    the parity bit (1.0 must stay pinned — a drop below baseline is the
    loudest possible regression), both timed legs (lower is better via
    the ``_ms`` marker) and the kernel/stock throughput ratio the
    promotion band reads."""
    out: Dict[str, float] = {}
    for k in doc.get("kernels") or []:
        name = k.get("name")
        if not name:
            continue
        out[f"{name}_parity"] = 1.0 if k.get("parity") else 0.0
        for key in ("stock_ms", "kernel_ms", "ratio"):
            v = k.get(key)
            if isinstance(v, (int, float)) and math.isfinite(v):
                out[f"{name}_{key}"] = float(v)
    return out


def flatten_dataservice_bench(doc: dict) -> Dict[str, float]:
    """The DSVC lane's series (``tools/io_bench.py --service``): the
    local-chain baseline and both service legs as img/sec (the 2-client
    aggregate is the multi-tenant amortization claim — a fall back
    toward the 1-client rate means clients stopped sharing decodes),
    plus the chunk-cache hit rate, which the lane pins > 0."""
    out: Dict[str, float] = {}
    sv = doc.get("service")
    if not isinstance(sv, dict):
        return out
    for key in ("local_img_per_sec", "service_1c_img_per_sec",
                "service_2c_img_per_sec", "blocks_produced"):
        v = sv.get(key)
        if isinstance(v, (int, float)) and math.isfinite(v):
            out[key] = float(v)
    hr = (sv.get("cache") or {}).get("hit_rate")
    if isinstance(hr, (int, float)) and math.isfinite(hr):
        out["cache_hit_rate"] = float(hr)
    return out


FLATTENERS = {"io_bench": flatten_io_bench,
              "dataservice_bench": flatten_dataservice_bench,
              "kernel_bench": flatten_kernel_bench,
              "crash_audit": flatten_crash_audit,
              "elastic_crash": flatten_elastic_crash,
              "serve_bench": flatten_serve_bench,
              "wire_bench": flatten_wire_bench,
              # the >= 10^6-request binary burst verdict
              # (fleet_smoke --no-kill --wire binary) shares the
              # fleet verdict shape but is its own series — mixing it
              # into fleet_bench would band the kill-lane numbers
              # against a different config
              "wire_burst": flatten_fleet_bench,
              "mesh_parity": flatten_mesh_parity,
              "quant_bench": flatten_quant_bench,
              "elastic": flatten_elastic,
              "fleet_bench": flatten_fleet_bench,
              "async_bench": flatten_async_bench,
              "tenant_bench": flatten_tenant_bench,
              "integrity_bench": flatten_integrity_bench}


# ----------------------------------------------------------------------
# history
def load_history(path: str, bench: str) -> List[dict]:
    """Prior entries of ``bench``, oldest first; torn/foreign lines are
    skipped (the file survives crashes and hand edits)."""
    out: List[dict] = []
    try:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    ent = json.loads(line)
                except ValueError:
                    continue
                if (isinstance(ent, dict) and ent.get("bench") == bench
                        and isinstance(ent.get("metrics"), dict)):
                    out.append(ent)
    except OSError:
        pass
    return out


def append_history(path: str, entry: dict) -> None:
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "a", encoding="utf-8") as f:
        f.write(json.dumps(entry, separators=(",", ":")) + "\n")
        f.flush()
        os.fsync(f.fileno())


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


# ----------------------------------------------------------------------
# comparison
def compare(bench: str, metrics: Dict[str, float], history: List[dict],
            window: int = 5, band: float = 0.2) -> dict:
    """Build the verdict document for one run vs the rolling baseline.

    ``history`` holds PRIOR entries only (the current run is appended
    separately, after comparison — a run must never be its own
    baseline)."""
    baseline: Dict[str, float] = {}
    tail = history[-window:]
    for name in metrics:
        prior = [e["metrics"][name] for e in tail
                 if isinstance(e["metrics"].get(name), (int, float))]
        if prior:
            baseline[name] = _median(prior)
    # orientation-aware noise banding shared with the self-tuning
    # controller's keep/rollback verdicts (cxxnet_tpu/tune): a bench
    # delta the controller would keep is exactly one the sentinel
    # would call an improvement, and vice versa
    from cxxnet_tpu.tune.controller import band_verdict

    regressions, improvements = [], []
    for name, value in sorted(metrics.items()):
        base = baseline.get(name)
        if base is None or base == 0:
            continue
        ratio = value / base
        verdict_ = band_verdict(value, base, band,
                                lower_is_better=lower_is_better(name))
        row = {"metric": name, "value": value, "baseline": base,
               "ratio": round(ratio, 4)}
        if verdict_ == "worse":
            regressions.append(row)
        elif verdict_ == "better":
            improvements.append(row)
    verdict = ("baseline" if not baseline
               else "regression" if regressions else "ok")
    return {
        "bench": bench,
        "ts": time.time(),
        "host": platform.node(),
        "metrics": metrics,
        "window": window,
        "noise_band": band,
        "history_len": len(history),
        "baseline": baseline or None,
        "regressions": regressions,
        "improvements": improvements,
        "verdict": verdict,
    }


def validate_verdict(doc: dict) -> List[str]:
    """Schema problems of a verdict document (empty == valid) — what
    the CI lane asserts; throughput itself is hardware weather."""
    problems: List[str] = []
    for key in ("bench", "ts", "metrics", "window", "noise_band",
                "history_len", "regressions", "improvements", "verdict"):
        if key not in doc:
            problems.append(f"verdict: missing key {key!r}")
    if doc.get("verdict") not in VERDICTS:
        problems.append(f"verdict: bad verdict {doc.get('verdict')!r}")
    if not isinstance(doc.get("metrics"), dict) or not doc.get("metrics"):
        problems.append("verdict: metrics missing/empty")
    else:
        for k, v in doc["metrics"].items():
            if not (isinstance(v, (int, float)) and math.isfinite(v)):
                problems.append(f"verdict: metric {k}={v!r} not finite")
    for key in ("regressions", "improvements"):
        for row in doc.get(key) or []:
            for f in ("metric", "value", "baseline", "ratio"):
                if f not in row:
                    problems.append(f"verdict: {key} row missing {f!r}")
    return problems


# ----------------------------------------------------------------------
def _emit_alert(doc: dict, event_log: str = "") -> None:
    """Regression → structured event + registry counter (in this
    process; a scraping service sees it when the guard runs embedded)."""
    from cxxnet_tpu.obs import events as obs_events
    from cxxnet_tpu.obs.registry import registry

    if event_log:
        obs_events.configure([("event_log", event_log)])
    registry().counter(
        "perf_regressions_total",
        "perf_guard verdicts that found a regression.",
        labelnames=("bench",),
    ).labels(bench=doc["bench"]).inc()
    worst = max(doc["regressions"], key=lambda r: abs(r["ratio"] - 1.0))
    obs_events.emit(
        "alert.perf_regression", bench=doc["bench"],
        regressions=[r["metric"] for r in doc["regressions"]],
        worst_metric=worst["metric"], worst_ratio=worst["ratio"],
        history_len=doc["history_len"])


def run_once(bench: str, input_doc: dict, history_path: str,
             window: int, band: float, event_log: str = "") -> dict:
    metrics = FLATTENERS[bench](input_doc)
    if not metrics:
        raise ValueError(
            f"perf_guard: no {bench} metrics found in the input document")
    history = load_history(history_path, bench)
    doc = compare(bench, metrics, history, window=window, band=band)
    append_history(history_path, {
        "ts": doc["ts"], "bench": bench, "host": doc["host"],
        "metrics": metrics,
    })
    if doc["verdict"] == "regression":
        try:
            _emit_alert(doc, event_log)
        except Exception as e:  # noqa: BLE001 - the verdict still stands
            print(f"# perf_guard: alert emission failed: {e}",
                  file=sys.stderr)
    return doc


# ----------------------------------------------------------------------
def _smoke(history_path: str, window: int, band: float) -> dict:
    """Two tiny real io_bench measurements through the full pipeline:
    the first seeds the history (verdict ``baseline``), the second
    compares against it — proving append, rolling baseline, banding and
    the verdict schema on real numbers in seconds."""
    import tempfile

    import io_bench

    docs = []
    with tempfile.TemporaryDirectory() as workdir:
        io_bench.generate_imgbin(workdir, 48, 48)
        for _ in range(2):
            rate, stages = io_bench.run_epoch(workdir, 48, 0)
            bench_doc = {"results": [{
                "mode": "serial", "img_per_sec": rate,
                "decode_augment_per_sec": rate, "stages": stages,
            }]}
            docs.append(run_once("io_bench", bench_doc, history_path,
                                 window, band))
    final = docs[-1]
    final["smoke"] = {"runs": len(docs),
                      "first_verdict": docs[0]["verdict"]}
    return final


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--bench", choices=sorted(FLATTENERS),
                    default="io_bench")
    ap.add_argument("--input", default="",
                    help="bench JSON report ('-' for stdin)")
    ap.add_argument("--history", default="bench_history.jsonl",
                    help="append-only history JSONL (committed format)")
    ap.add_argument("--window", type=int, default=5,
                    help="rolling-baseline width (prior runs)")
    ap.add_argument("--band", type=float, default=0.2,
                    help="noise band around the baseline (fraction)")
    ap.add_argument("--json", dest="json_path", default="",
                    help="also write the verdict document here")
    ap.add_argument("--event-log", default="",
                    help="persist regression alert events to this JSONL")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on a regression verdict")
    ap.add_argument("--smoke", action="store_true",
                    help="two tiny real runs end to end (CI lane)")
    args = ap.parse_args()

    if args.smoke:
        doc = _smoke(args.history, args.window, args.band)
    else:
        if not args.input:
            ap.error("--input is required (or use --smoke)")
        if args.input == "-":
            input_doc = json.load(sys.stdin)
        else:
            with open(args.input, "r", encoding="utf-8") as f:
                input_doc = json.load(f)
        doc = run_once(args.bench, input_doc, args.history,
                       args.window, args.band, event_log=args.event_log)

    problems = validate_verdict(doc)
    print(json.dumps(doc, indent=1))
    if args.json_path:
        with open(args.json_path, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1)
    for p in problems:
        print(f"FAIL {p}", file=sys.stderr)
    if problems:
        return 1
    if args.strict and doc["verdict"] == "regression":
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
