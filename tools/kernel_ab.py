"""On-chip kernel library bisect A/B (``cxxnet_tpu/ops/kernels/``).

The promotion discipline for the Pallas block kernels — the
``wino_bf16_ab.py --bembed-only`` shape applied per kernel.  For each
of ``conv_block`` / ``int8_gemm`` / ``zero_update``, three stages:

1. **interpret-parity gate** — the kernel (interpret mode off-TPU, the
   compiled Mosaic program on TPU) vs the JITTED stock lowering,
   ``np.array_equal`` over the workload shapes.  The reference is the
   jitted stock function, not an eager replay: the net's real programs
   are always compiled, and on CPU the eager op-by-op spelling differs
   from its own compiled form (FMA fusion) — "parity with the stock
   lowering" means the lowering.  A mismatch hard-fails the run; no
   timing happens on wrong math.
2. **timed legs** — alternating stock/kernel reps (the bisect
   discipline: interleaving lands machine drift on both legs), median
   wall per leg.  Each leg is a standalone jit instrumented as
   ``kind=kernel_<name>`` so per-kernel ``xla_program_*`` families land
   in the registry next to the ``kernel_selected`` gauge.
3. **verdict** — PROMOTE iff parity holds and the kernel/stock
   throughput ratio is >= 0.9 (the branch-embed band: a kernel may ride
   a tie, never a regression); REJECT otherwise.  ``--record`` writes
   the verdict for the measured backend into
   ``ops/kernels/verdicts.json`` — the committed state ``kernel_lib =
   auto`` follows.  On CPU the Pallas paths run under the interpreter
   (emulation), so CPU verdicts are honest rejects; the TPU
   invocations live in ``tools/tpu_queue.sh``.

Each kernel's numbers also flow through ``perf_guard`` (bench
``kernel_bench``): the appended history makes later runs comparable
and the emitted per-kernel verdict document is schema-validated here —
a malformed verdict fails the run, not the reader.

Usage:
    python tools/kernel_ab.py [--kernel name[,name...]] [--smoke]
        [--record] [--json PATH] [--history PATH]
"""

import argparse
import functools
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

PROMOTE_RATIO = 0.9  # same band as the branch-embed CPU verdict


def _median(vals):
    s = sorted(vals)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def _instrumented(fn, name):
    """A standalone jit accounted as ``kind=kernel_<name>`` — the
    per-kernel ``xla_program_flops/bytes/compile_seconds`` families."""
    import jax

    from cxxnet_tpu.obs import device as obs_device

    return obs_device.instrument(jax.jit(fn), kind=f"kernel_{name}",
                                 data_arg=0)


def _time_legs(legs, reps):
    """Alternate the (already-warm) legs ``reps`` times; median seconds
    per leg name."""
    import jax

    walls = {name: [] for name, _ in legs}
    for _ in range(reps):
        for name, fn in legs:
            t0 = time.perf_counter()
            jax.block_until_ready(fn())
            walls[name].append(time.perf_counter() - t0)
    return {name: _median(v) for name, v in walls.items()}


# ----------------------------------------------------------------------
# per-kernel workloads: (build) -> dict with parity + timings
def ab_conv_block(smoke, interpret, reps):
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from cxxnet_tpu.ops.kernels import conv_block

    b, hw, cin, cout = (4, 8, 16, 32) if smoke else (32, 28, 64, 256)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(b, hw, hw, cin).astype(np.float32))
    wk = jnp.asarray(rng.randn(1, 1, cin, cout).astype(np.float32) * 0.1)
    bias = jnp.asarray(rng.randn(cout).astype(np.float32))

    def stock(x):
        y = lax.conv_general_dilated(
            x, wk, window_strides=(1, 1), padding=((0, 0), (0, 0)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return y + bias.astype(x.dtype)

    kern = functools.partial(conv_block.conv1x1_block, wk=wk, bias=bias,
                             interpret=interpret)
    f_stock = _instrumented(stock, "conv_block")
    f_kern = _instrumented(lambda x: kern(x), "conv_block")
    a, k = f_stock(x), f_kern(x)
    parity = bool(np.array_equal(np.asarray(a), np.asarray(k)))
    walls = _time_legs([("stock", lambda: f_stock(x)),
                        ("kernel", lambda: f_kern(x))], reps)
    return parity, walls, f"b{b} {hw}x{hw} {cin}->{cout} f32"


def ab_int8_gemm(smoke, interpret, reps):
    import jax.numpy as jnp
    import numpy as np

    from cxxnet_tpu.ops import quant as opsq
    from cxxnet_tpu.ops.kernels import int8_gemm

    m, k_dim, o = (8, 32, 16) if smoke else (128, 512, 1024)
    rng = np.random.RandomState(1)
    w = rng.randn(o, k_dim).astype(np.float32)
    q, s = opsq.quantize_weight(w, out_axis=0)
    lp = {opsq.QKEY: jnp.asarray(q), opsq.SKEY: jnp.asarray(s),
          "bias": jnp.asarray(rng.randn(o).astype(np.float32))}
    x = jnp.asarray(rng.randn(m, k_dim).astype(np.float32))

    f_stock = _instrumented(lambda x: opsq.fc_apply_q(lp, x), "int8_gemm")
    f_kern = _instrumented(
        lambda x: int8_gemm.int8_gemm_rescale(
            x, lp[opsq.QKEY], lp[opsq.SKEY], lp["bias"],
            interpret=interpret),
        "int8_gemm")
    a, kk = f_stock(x), f_kern(x)
    parity = bool(np.array_equal(np.asarray(a), np.asarray(kk)))
    walls = _time_legs([("stock", lambda: f_stock(x)),
                        ("kernel", lambda: f_kern(x))], reps)
    return parity, walls, f"{m}x{k_dim} @ int8 {o}ch f32-act"


def ab_zero_update(smoke, interpret, reps):
    import jax.numpy as jnp
    import numpy as np

    from cxxnet_tpu.ops.kernels import update_step
    from cxxnet_tpu.updater import SGDUpdater

    shape = (3, 3, 8, 16) if smoke else (3, 3, 256, 512)
    up = SGDUpdater("wmat")
    for k, v in (("eta", "0.05"), ("momentum", "0.9"), ("wd", "0.0005"),
                 ("clip_gradient", "1.0")):
        up.set_param(k, v)
    rng = np.random.RandomState(2)
    w = jnp.asarray(rng.randn(*shape).astype(np.float32))
    g = jnp.asarray(rng.randn(*shape).astype(np.float32))
    mom = jnp.asarray(rng.randn(*shape).astype(np.float32))
    epoch = jnp.asarray(3)
    p = up.param

    f_stock = _instrumented(
        lambda w: up.apply(w, g, {"m": mom}, epoch), "zero_update")
    f_kern = _instrumented(
        lambda w: update_step.sgd_update(
            w, g, mom, p.learning_rate(epoch).astype(w.dtype),
            p.momentum_at(epoch).astype(w.dtype),
            wd=p.wd, clip=p.clip_gradient, interpret=interpret),
        "zero_update")
    (w1, s1), (w2, m2) = f_stock(w), f_kern(w)
    parity = bool(np.array_equal(np.asarray(w1), np.asarray(w2))
                  and np.array_equal(np.asarray(s1["m"]), np.asarray(m2)))
    walls = _time_legs([("stock", lambda: f_stock(w)),
                        ("kernel", lambda: f_kern(w))], reps)
    return parity, walls, f"sgd {'x'.join(map(str, shape))} f32 clip"


AB = {"conv_block": ab_conv_block,
      "int8_gemm": ab_int8_gemm,
      "zero_update": ab_zero_update}


# ----------------------------------------------------------------------
def run_kernel(name, smoke, backend, reps):
    interpret = backend != "tpu"
    parity, walls, workload = AB[name](smoke, interpret, reps)
    stock_ms = walls["stock"] * 1e3
    kernel_ms = walls["kernel"] * 1e3
    ratio = stock_ms / kernel_ms if kernel_ms > 0 else 0.0
    verdict = ("promote" if parity and ratio >= PROMOTE_RATIO
               else "reject")
    reasons = []
    if not parity:
        reasons.append("parity gate failed")
    if ratio < PROMOTE_RATIO:
        reasons.append(f"throughput ratio {ratio:.3f} < {PROMOTE_RATIO}"
                       + (" (interpret-mode emulation)" if interpret
                          else ""))
    return {"name": name, "workload": workload, "parity": parity,
            "stock_ms": round(stock_ms, 4),
            "kernel_ms": round(kernel_ms, 4),
            "ratio": round(ratio, 4), "verdict": verdict,
            "reasons": reasons}


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--kernel", default="",
                    help="comma list (default: all three)")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes + few reps (the KERNEL=1 lane)")
    ap.add_argument("--reps", type=int, default=0,
                    help="timing reps per leg (default 5, smoke 3)")
    ap.add_argument("--record", action="store_true",
                    help="write the verdicts into ops/kernels/"
                         "verdicts.json for the measured backend")
    ap.add_argument("--json", dest="json_path", default="",
                    help="write the full report document here")
    ap.add_argument("--history", default="",
                    help="perf_guard history JSONL (appends one "
                         "kernel_bench entry per kernel)")
    args = ap.parse_args()

    import jax

    import perf_guard
    from cxxnet_tpu.ops import kernels as klib

    backend = jax.default_backend()
    names = ([s.strip() for s in args.kernel.split(",") if s.strip()]
             or sorted(AB))
    bad = [n for n in names if n not in AB]
    if bad:
        ap.error(f"unknown kernel(s) {bad}; known: {sorted(AB)}")
    reps = args.reps or (3 if args.smoke else 5)

    report = {"tool": "kernel_ab", "backend": backend,
              "smoke": bool(args.smoke), "reps": reps,
              "promote_ratio": PROMOTE_RATIO, "kernels": []}
    rc = 0
    for name in names:
        res = run_kernel(name, args.smoke, backend, reps)
        report["kernels"].append(res)
        print(f"# {name} [{backend}] {res['workload']}: parity="
              f"{'OK' if res['parity'] else 'FAIL'} stock "
              f"{res['stock_ms']:.3f}ms kernel {res['kernel_ms']:.3f}ms "
              f"ratio {res['ratio']:.3f} -> {res['verdict'].upper()}"
              + (f" ({'; '.join(res['reasons'])})" if res["reasons"]
                 else ""), file=sys.stderr)
        if not res["parity"]:
            rc = 1
        if args.history:
            # one schema-validated perf_guard verdict per kernel — the
            # same document the opt-in lanes commit to their histories
            doc = perf_guard.run_once(
                "kernel_bench", {"backend": backend, "kernels": [res]},
                args.history, window=5, band=0.2)
            problems = perf_guard.validate_verdict(doc)
            for p in problems:
                print(f"FAIL {name}: {p}", file=sys.stderr)
                rc = 1
        if args.record:
            klib.record_verdict(
                name, backend, res["verdict"], ratio=res["ratio"],
                parity=res["parity"], stock_ms=res["stock_ms"],
                kernel_ms=res["kernel_ms"], smoke=bool(args.smoke),
                interpret=backend != "tpu",
                ts=time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                tool="kernel_ab")
            print(f"# recorded {name}/{backend}: {res['verdict']}",
                  file=sys.stderr)
    print(json.dumps(report, indent=1))
    if args.json_path:
        with open(args.json_path, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=1)
    return rc


if __name__ == "__main__":
    sys.exit(main())
