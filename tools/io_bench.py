"""Input-pipeline throughput benchmark (host JPEG decode rate).

Generates a synthetic JPEG imgbin (+ .lst), then drives the CLI
``test_io = 1`` path — the reference's IO-isolation mode
(``cxxnet_main.cpp`` ``test_io``) — through the full chain
imgbin → native C++ decode pool → augment (crop + mirror) →
batch → threadbuffer, sweeping ``decode_thread``.

Prints one ``img/s`` line per thread count; results are recorded in
``doc/io.md``.  The pipeline's job is to out-run the device step rate
(SURVEY §7 hard part (c)): compare against bench.py's images/sec/chip.

Usage: python tools/io_bench.py [n_images] [size] [threads,threads,...]
"""

from __future__ import annotations

import io
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def generate_imgbin(workdir: str, n: int, size: int) -> None:
    """n synthetic photo-like JPEGs (smooth gradients + texture — noise
    JPEGs would decode unrealistically slowly) + the matching .lst."""
    from PIL import Image

    from cxxnet_tpu.io.imgbin import BinPageWriter

    rng = np.random.RandomState(0)
    writer = BinPageWriter(os.path.join(workdir, "bench.bin"))
    with open(os.path.join(workdir, "bench.lst"), "w") as lst:
        yy, xx = np.mgrid[0:size, 0:size].astype(np.float32)
        for i in range(n):
            base = (
                128
                + 100 * np.sin(xx / (7 + i % 13) + i)
                + 60 * np.cos(yy / (5 + i % 7))
            )
            img = np.stack(
                [base, np.roll(base, i % size, 0), base.T], axis=-1
            )
            img += rng.randn(size, size, 3) * 8
            pil = Image.fromarray(
                np.clip(img, 0, 255).astype(np.uint8), "RGB"
            )
            buf = io.BytesIO()
            pil.save(buf, "JPEG", quality=85)
            writer.push(buf.getvalue())
            lst.write(f"{i}\t{i % 10}\tsynth_{i}.jpg\n")
    writer.close()


def run_epoch(workdir: str, n: int, size: int, threads: int,
              native: int = 1) -> float:
    """One full pass of the train iterator chain; returns images/sec."""
    from cxxnet_tpu import config as cfgmod
    from cxxnet_tpu.io.data import create_iterator

    crop = size - size // 8
    conf = f"""
data = train
iter = imgbin
  image_bin = {workdir}/bench.bin
  image_list = {workdir}/bench.lst
  native_decoder = {native}
  decode_thread = {threads}
  silent = 1
  rand_crop = 1
  rand_mirror = 1
  input_shape = 3,{crop},{crop}
  batch_size = 32
  round_batch = 0
  label_width = 1
iter = threadbuffer
iter = end
"""
    sec = cfgmod.split_sections(cfgmod.parse_pairs(conf)).find("data")[0]
    it = create_iterator(sec.entries)
    it.init()
    # warm one epoch (library build, page cache)
    it.before_first()
    while it.next():
        pass
    it.before_first()
    t0 = time.perf_counter()
    got = 0
    while it.next():
        got += it.value().data.shape[0]
    dt = time.perf_counter() - t0
    if hasattr(it, "close"):
        it.close()
    return got / dt


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 2000
    size = int(sys.argv[2]) if len(sys.argv) > 2 else 256
    threads = (
        [int(t) for t in sys.argv[3].split(",")]
        if len(sys.argv) > 3
        else [1, 2, 4, 8, 0]
    )
    import tempfile

    with tempfile.TemporaryDirectory() as workdir:
        t0 = time.perf_counter()
        generate_imgbin(workdir, n, size)
        print(
            f"# generated {n} JPEGs ({size}x{size}) in "
            f"{time.perf_counter() - t0:.1f}s",
            flush=True,
        )
        rate_py = run_epoch(workdir, n, size, 1, native=0)
        print(f"python-decode fallback: {rate_py:8.1f} img/s", flush=True)
        for t in threads:
            rate = run_epoch(workdir, n, size, t)
            label = "auto" if t == 0 else str(t)
            print(f"decode_thread = {label:>4}: {rate:8.1f} img/s", flush=True)


if __name__ == "__main__":
    main()
