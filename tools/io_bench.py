"""Input-pipeline throughput benchmark with per-stage breakdown.

Generates a synthetic JPEG imgbin (+ .lst), then measures the host data
pipeline two ways per mode:

* **decode+augment rows/sec** — the instance-level rate of the
  decode/augment stage itself (imgbin → ParallelAugment chain driven
  record by record), the number the parallel pool exists to raise;
* **img/sec to batches** — the full train chain (… → batch →
  threadbuffer), i.e. what the train loop actually sees.

Modes: the serial path and a ``num_decode_workers`` sweep (python
decode pool, ``io/pipeline.py``); ``--native`` adds the C++ reader
sweep over ``decode_thread`` when the native extension builds.

``--json out.json`` writes the machine-readable report: one entry per
mode with both rates plus the :class:`~cxxnet_tpu.utils.profiler.
PipelineStats` snapshot (decode / augment / batch / h2d / device_wait
rows-per-sec and percentiles).  ``--smoke`` runs a tiny set and
validates the JSON schema — the ``PERF=1`` lane of
``tools/run_tier1.sh`` (no throughput assertions in CI: rates are
hardware-dependent, the schema is not).

Usage:
  python tools/io_bench.py [n_images] [size] [workers,workers,...]
  python tools/io_bench.py --json /tmp/io.json
  python tools/io_bench.py --smoke
"""

from __future__ import annotations

import argparse
import io
import json
import math
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

STAGES = ("decode", "augment", "batch", "h2d", "device_wait")


def generate_imgbin(workdir: str, n: int, size: int) -> None:
    """n synthetic photo-like JPEGs (smooth gradients + texture — noise
    JPEGs would decode unrealistically slowly) + the matching .lst."""
    from PIL import Image

    from cxxnet_tpu.io.imgbin import BinPageWriter

    rng = np.random.RandomState(0)
    writer = BinPageWriter(os.path.join(workdir, "bench.bin"))
    with open(os.path.join(workdir, "bench.lst"), "w") as lst:
        yy, xx = np.mgrid[0:size, 0:size].astype(np.float32)
        for i in range(n):
            base = (
                128
                + 100 * np.sin(xx / (7 + i % 13) + i)
                + 60 * np.cos(yy / (5 + i % 7))
            )
            img = np.stack(
                [base, np.roll(base, i % size, 0), base.T], axis=-1
            )
            img += rng.randn(size, size, 3) * 8
            pil = Image.fromarray(
                np.clip(img, 0, 255).astype(np.uint8), "RGB"
            )
            buf = io.BytesIO()
            pil.save(buf, "JPEG", quality=85)
            writer.push(buf.getvalue())
            lst.write(f"{i}\t{i % 10}\tsynth_{i}.jpg\n")
    writer.close()


def _iter_params(workdir: str, size: int, workers: int, native: int,
                 decode_thread: int):
    crop = size - size // 8
    return [
        ("image_bin", f"{workdir}/bench.bin"),
        ("image_list", f"{workdir}/bench.lst"),
        ("native_decoder", str(native)),
        ("decode_thread", str(decode_thread)),
        ("num_decode_workers", str(workers)),
        ("silent", "1"),
        ("rand_crop", "1"),
        ("rand_mirror", "1"),
        ("input_shape", f"3,{crop},{crop}"),
        ("batch_size", "32"),
        ("round_batch", "0"),
        ("label_width", "1"),
    ]


def run_instances(workdir: str, size: int, workers: int,
                  native: int = 0, decode_thread: int = 1,
                  queue_depth: int = 0) -> float:
    """Decode+augment stage rate: drive the instance-level chain
    (imgbin → parallel/serial augment) directly; rows/sec."""
    from cxxnet_tpu.io.augment import AugmentIterator
    from cxxnet_tpu.io.imgbin import ImageBinIterator
    from cxxnet_tpu.io.pipeline import ParallelAugmentIterator

    it = ParallelAugmentIterator(AugmentIterator(ImageBinIterator()))
    for k, v in _iter_params(workdir, size, workers, native, decode_thread):
        it.set_param(k, v)
    if queue_depth:
        it.set_param("decode_queue_depth", str(queue_depth))
    it.init()
    it.before_first()
    while it.next():  # warm epoch (page cache, pool spin-up)
        pass
    it.before_first()
    t0 = time.perf_counter()
    got = 0
    while it.next():
        got += 1
    dt = time.perf_counter() - t0
    it.close()
    return got / dt


def run_epoch(workdir: str, size: int, workers: int, native: int = 0,
              decode_thread: int = 1, h2d: bool = False):
    """Full-chain rate (imgbin → augment → batch → threadbuffer) plus
    the per-stage snapshot; optionally transfers every batch to the
    JAX device so the ``h2d`` stage is exercised."""
    from cxxnet_tpu import config as cfgmod
    from cxxnet_tpu.io.data import create_iterator
    from cxxnet_tpu.utils.profiler import pipeline_stats

    entries = (
        [("iter", "imgbin")]
        + _iter_params(workdir, size, workers, native, decode_thread)
        + [("iter", "threadbuffer"), ("silent", "1"), ("iter", "end")]
    )
    del cfgmod  # parsing not needed for an explicit entry list
    it = create_iterator(entries)
    it.init()
    it.before_first()
    while it.next():  # warm epoch
        pass
    if h2d:
        import jax
        import jax.numpy as jnp

        jax.block_until_ready(jnp.zeros(8))  # backend + transfer warmup
    pipeline_stats().reset()
    it.before_first()
    t0 = time.perf_counter()
    got = 0
    while it.next():
        batch = it.value()
        got += batch.data.shape[0]
        if h2d:
            th0 = time.perf_counter()
            arr = jnp.asarray(batch.data)
            pipeline_stats().add("h2d", time.perf_counter() - th0,
                                 rows=batch.data.shape[0])
            tw0 = time.perf_counter()
            jax.block_until_ready(arr)
            pipeline_stats().add("device_wait", time.perf_counter() - tw0,
                                 rows=batch.data.shape[0])
    dt = time.perf_counter() - t0
    it.close()
    return got / dt, pipeline_stats().snapshot()


def _build_instance_chain(workdir: str, size: int, workers: int,
                          queue_depth: int = 0):
    from cxxnet_tpu.io.augment import AugmentIterator
    from cxxnet_tpu.io.imgbin import ImageBinIterator
    from cxxnet_tpu.io.pipeline import ParallelAugmentIterator

    it = ParallelAugmentIterator(AugmentIterator(ImageBinIterator()))
    for k, v in _iter_params(workdir, size, workers, 0, 1):
        it.set_param(k, v)
    if queue_depth:
        it.set_param("decode_queue_depth", str(queue_depth))
    it.init()
    return it


def timed_rate(workdir: str, size: int, workers: int,
               queue_depth: int = 0, seconds: float = 4.0) -> float:
    """Steady-state decode+augment rows/sec over a FIXED duration of
    continuous epochs (warm epoch first).  Duration-based measurement
    — a single tiny epoch is far too short to be stable, and autotune
    verdicts compare these numbers against each other."""
    it = _build_instance_chain(workdir, size, workers, queue_depth)
    it.before_first()
    while it.next():  # warm epoch (page cache, pool spin-up)
        pass
    n = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < seconds:
        it.before_first()
        while it.next():
            n += 1
            if time.perf_counter() - t0 >= seconds:
                break
    dt = time.perf_counter() - t0
    it.close()
    return n / dt


def run_autotune(workdir: str, size: int, seconds: float,
                 period_s: float, band: float,
                 threshold: float = 0.9,
                 measure_seconds: float = 4.0) -> dict:
    """Bad-knobs recovery: start the decode chain at deliberately bad
    settings (1 worker, in-flight window 1), let the self-tuning
    controller (``cxxnet_tpu/tune``) hill-climb them against the live
    consumption rate for ``seconds``, then re-measure cleanly with the
    knobs the controller chose and compare against a hand-tuned
    reference.  All three reference numbers (bad / hand / tuned) come
    from :func:`timed_rate` — the same steady-state, duration-based
    measurement — so the recovery ratio compares like with like.  The
    TUNE=1 CI lane asserts ``recovery_ratio >= threshold`` (the
    ROADMAP item-5 acceptance bar)."""
    from cxxnet_tpu.tune import KnobController, pipeline_knobs

    cpu = os.cpu_count() or 2
    hand_workers = max(2, min(4, cpu))
    bad_rate = timed_rate(workdir, size, 1, queue_depth=1,
                          seconds=measure_seconds)

    it = _build_instance_chain(workdir, size, 1, queue_depth=1)
    rows = [0]
    ctrl = KnobController(
        lambda: float(rows[0]), pipeline_knobs(it),
        period_s=period_s, band=band, name="io_bench",
    )
    ctrl.start()
    t_end = time.monotonic() + seconds
    epochs = 0
    try:
        while time.monotonic() < t_end:
            it.before_first()
            while it.next():
                rows[0] += 1
                if time.monotonic() >= t_end:
                    break
            epochs += 1
    finally:
        ctrl.stop()
    tuned = ctrl.snapshot()
    tuned_workers = int(tuned["knobs"]["num_decode_workers"])
    tuned_queue = int(tuned["knobs"]["decode_queue_depth"])
    it.close()
    # clean re-measures with the chosen knobs vs the hand-tuned
    # reference, INTERLEAVED back to back so slow machine-load drift
    # (CPU frequency, page cache, sibling processes) hits both legs
    # equally — measuring hand up front and tuned minutes later made
    # the recovery ratio hostage to whatever changed in between
    tuned_runs, hand_runs = [], []
    half = max(1.0, measure_seconds / 2.0)
    for _ in range(2):
        tuned_runs.append(timed_rate(workdir, size, tuned_workers,
                                     queue_depth=tuned_queue,
                                     seconds=half))
        hand_runs.append(timed_rate(workdir, size, hand_workers,
                                    seconds=half))
    tuned_rate = max(tuned_runs)
    hand_rate = max(hand_runs)
    chain_rate, stages = run_epoch(workdir, size, tuned_workers)
    recovery = tuned_rate / hand_rate if hand_rate > 0 else 0.0
    return {
        "autotune": {
            "seconds": seconds,
            "period_s": period_s,
            "band": band,
            "epochs": epochs,
            "rows_consumed": rows[0],
            "initial": {"num_decode_workers": 1, "decode_queue_depth": 1,
                        "decode_augment_per_sec": bad_rate},
            "hand": {"num_decode_workers": hand_workers,
                     "decode_augment_per_sec": hand_rate},
            "tuned": {"num_decode_workers": tuned_workers,
                      "decode_queue_depth": tuned_queue,
                      "decode_augment_per_sec": tuned_rate},
            "controller": tuned,
            "recovery_ratio": recovery,
            "threshold": threshold,
            "ok": bool(recovery >= threshold),
        },
        "results": [{
            "mode": "autotuned", "img_per_sec": chain_rate,
            "decode_augment_per_sec": tuned_rate, "stages": stages,
        }],
    }


def validate_autotune(doc: dict) -> None:
    """Schema check for the ``--autotune`` verdict document (the TUNE=1
    lane's contract — obs_dump --check style); raises ValueError."""
    at = doc.get("autotune")
    if not isinstance(at, dict):
        raise ValueError("autotune report: missing autotune section")
    for key in ("initial", "hand", "tuned", "recovery_ratio",
                "threshold", "ok", "controller"):
        if key not in at:
            raise ValueError(f"autotune report: missing key {key!r}")
    for leg in ("initial", "hand", "tuned"):
        v = at[leg].get("decode_augment_per_sec")
        if not (isinstance(v, (int, float)) and math.isfinite(v) and v > 0):
            raise ValueError(f"autotune report: bad {leg} rate {v!r}")
    if not isinstance(at["ok"], bool):
        raise ValueError("autotune report: ok must be a bool")
    for row in doc.get("results", []):
        for key in ("mode", "img_per_sec", "decode_augment_per_sec",
                    "stages"):
            if key not in row:
                raise ValueError(f"autotune report: result missing {key!r}")


def _drive_epoch(it, epoch: int) -> int:
    """One anchored pass (the CLI's before_first + augment_epoch
    sequence); rows consumed."""
    it.before_first()
    it.set_param("augment_epoch", str(epoch))
    rows = 0
    while it.next():
        rows += it.value().data.shape[0]
    return rows


def run_service_ab(workdir: str, size: int) -> dict:
    """Local vs data-service A/B over the same imgbin decode chain:

    * **local** — the in-process chain, one warm timed epoch;
    * **service_1c** — one ``iter = service`` client against an
      in-process :class:`DataServiceServer`, timed on the warm (cached)
      epoch — the steady state a shared tenant sees;
    * **service_2c** — two concurrent clients on the warm cache,
      aggregate rows/sec: the multi-tenant amortization the service
      exists for (decode once, serve N).

    The verdict carries the server's cache stats; the DSVC lane asserts
    ``hit_rate > 0`` (a service that re-decodes per client is broken)."""
    import threading

    from cxxnet_tpu.io.data import create_iterator
    from cxxnet_tpu.io.dataservice.server import DataServiceServer

    sec = [("iter", "imgbin")] + _iter_params(workdir, size, 2, 0, 1)
    local = create_iterator(sec)
    local.init()
    _drive_epoch(local, 0)  # warm (page cache, pool spin-up)
    t0 = time.perf_counter()
    rows = _drive_epoch(local, 0)
    local_rate = rows / (time.perf_counter() - t0)
    local.close()

    srv = DataServiceServer(sec, [], cache_bytes=512 << 20, silent=True)
    srv.start()

    def make_client():
        it = create_iterator([
            ("iter", "service"),
            ("data_service_addr", f"127.0.0.1:{srv.port}"),
            ("batch_size", "32"),
            ("silent", "1"),
        ])
        it.init()
        return it

    try:
        c = make_client()
        _drive_epoch(c, 0)  # cold pass: the server decodes + caches
        t0 = time.perf_counter()
        rows = _drive_epoch(c, 0)
        svc1 = rows / (time.perf_counter() - t0)
        c.close()
        clients = [make_client() for _ in range(2)]
        totals = [0, 0]

        def consume(i):
            totals[i] = _drive_epoch(clients[i], 0)

        threads = [threading.Thread(target=consume, args=(i,))
                   for i in range(2)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        svc2 = sum(totals) / dt
        for it2 in clients:
            it2.close()
        stats = srv.statsz()
    finally:
        srv.close()
    return {
        "local_img_per_sec": local_rate,
        "service_1c_img_per_sec": svc1,
        "service_2c_img_per_sec": svc2,
        "blocks_produced": stats["blocks_produced"],
        "cache": stats["cache"],
    }


def validate_service(doc: dict) -> None:
    """Schema check for the ``--service`` verdict (the DSVC lane's
    contract); raises ValueError — including on a zero cache hit rate,
    which means the shared fleet re-decoded for every client."""
    sv = doc.get("service")
    if not isinstance(sv, dict):
        raise ValueError("service report: missing service section")
    for key in ("local_img_per_sec", "service_1c_img_per_sec",
                "service_2c_img_per_sec"):
        v = sv.get(key)
        if not (isinstance(v, (int, float)) and math.isfinite(v)
                and v > 0):
            raise ValueError(f"service report: bad {key}: {v!r}")
    cache = sv.get("cache")
    if not isinstance(cache, dict):
        raise ValueError("service report: missing cache stats")
    hr = cache.get("hit_rate")
    if not (isinstance(hr, (int, float)) and math.isfinite(hr)):
        raise ValueError(f"service report: bad hit_rate: {hr!r}")
    if hr <= 0:
        raise ValueError(
            "service report: cache hit_rate is 0 — the warm service "
            "epochs never hit the chunk cache")


def validate_report(doc: dict) -> None:
    """Schema check for the JSON report; raises ValueError on drift.
    This is what the CI smoke lane asserts — not throughput."""
    for key in ("n_images", "size", "results"):
        if key not in doc:
            raise ValueError(f"io_bench report: missing key {key!r}")
    if not doc["results"]:
        raise ValueError("io_bench report: empty results")
    for row in doc["results"]:
        for key in ("mode", "img_per_sec", "decode_augment_per_sec",
                    "stages"):
            if key not in row:
                raise ValueError(
                    f"io_bench report: result missing {key!r}: {row}"
                )
        for rate_key in ("img_per_sec", "decode_augment_per_sec"):
            v = row[rate_key]
            if not (isinstance(v, (int, float)) and math.isfinite(v)
                    and v >= 0):
                raise ValueError(
                    f"io_bench report: bad {rate_key}: {v!r}")
        for stage in STAGES:
            if stage not in row["stages"]:
                raise ValueError(
                    f"io_bench report: stage {stage!r} missing in "
                    f"{row['mode']}")
            srow = row["stages"][stage]
            fields = ["count", "rows", "total_s", "rows_per_sec"]
            if srow.get("count"):
                # active stages also carry the window-consistent timing
                # summary: mean_ms covers the same sliding window as the
                # percentiles, lifetime_mean_ms the whole epoch
                fields += ["mean_ms", "lifetime_mean_ms", "p50_ms"]
            for field in fields:
                v = srow.get(field)
                if not (isinstance(v, (int, float)) and math.isfinite(v)
                        and v >= 0):
                    raise ValueError(
                        f"io_bench report: stage {stage}.{field} bad: "
                        f"{v!r}")
    if "speedup_vs_serial" in doc:
        for k, v in doc["speedup_vs_serial"].items():
            if not (isinstance(v, (int, float)) and math.isfinite(v)):
                raise ValueError(f"io_bench report: bad speedup {k}={v!r}")


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("n_images", nargs="?", type=int, default=2000)
    ap.add_argument("size", nargs="?", type=int, default=256)
    ap.add_argument("workers", nargs="?", default="0,1,2,4,8",
                    help="num_decode_workers sweep (0 = serial path)")
    ap.add_argument("--json", dest="json_path", default="",
                    help="write the machine-readable report here")
    ap.add_argument("--h2d", action="store_true",
                    help="also measure host->device transfer per batch")
    ap.add_argument("--native", action="store_true",
                    help="additionally sweep the native C++ decoder")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny set + schema validation (CI lane)")
    ap.add_argument("--service", action="store_true",
                    help="A/B the data service: local chain vs 1 and 2 "
                         "service clients on a shared decode fleet "
                         "(DSVC lane)")
    ap.add_argument("--autotune", action="store_true",
                    help="bad-knobs recovery via the tune controller "
                         "(TUNE=1 lane); exits 1 below --recovery")
    ap.add_argument("--autotune-seconds", type=float, default=25.0)
    ap.add_argument("--tune-period", type=float, default=0.5)
    ap.add_argument("--tune-band", type=float, default=0.1)
    ap.add_argument("--recovery", type=float, default=0.9,
                    help="autotune pass bar vs the hand-tuned rate")
    args = ap.parse_args()

    if args.autotune:
        import tempfile

        with tempfile.TemporaryDirectory() as workdir:
            t0 = time.perf_counter()
            generate_imgbin(workdir, args.n_images, args.size)
            print(f"# generated {args.n_images} JPEGs "
                  f"({args.size}x{args.size}) in "
                  f"{time.perf_counter() - t0:.1f}s", flush=True)
            doc = run_autotune(workdir, args.size, args.autotune_seconds,
                               args.tune_period, args.tune_band,
                               threshold=args.recovery)
        validate_autotune(doc)
        at = doc["autotune"]
        print(f"# autotune: bad "
              f"{at['initial']['decode_augment_per_sec']:.1f} rows/s -> "
              f"tuned {at['tuned']['decode_augment_per_sec']:.1f} rows/s "
              f"(workers={at['tuned']['num_decode_workers']}, "
              f"queue={at['tuned']['decode_queue_depth']}) vs hand "
              f"{at['hand']['decode_augment_per_sec']:.1f} rows/s "
              f"(workers={at['hand']['num_decode_workers']}): "
              f"recovery {at['recovery_ratio']:.2f} "
              f"({'OK' if at['ok'] else 'FAIL'} at >= {at['threshold']})",
              flush=True)
        if args.json_path:
            with open(args.json_path, "w", encoding="utf-8") as f:
                json.dump(doc, f, indent=2)
            print(f"# report -> {args.json_path}", flush=True)
        sys.exit(0 if at["ok"] else 1)

    if args.service:
        import tempfile

        if args.smoke:
            args.n_images, args.size = 48, 48
        with tempfile.TemporaryDirectory() as workdir:
            t0 = time.perf_counter()
            generate_imgbin(workdir, args.n_images, args.size)
            print(f"# generated {args.n_images} JPEGs "
                  f"({args.size}x{args.size}) in "
                  f"{time.perf_counter() - t0:.1f}s", flush=True)
            doc = {"n_images": args.n_images, "size": args.size,
                   "service": run_service_ab(workdir, args.size)}
        validate_service(doc)
        sv = doc["service"]
        print(f"# data service: local {sv['local_img_per_sec']:.1f} "
              f"img/s, 1 client {sv['service_1c_img_per_sec']:.1f} "
              f"img/s, 2 clients {sv['service_2c_img_per_sec']:.1f} "
              f"img/s aggregate, cache hit rate "
              f"{sv['cache']['hit_rate']:.2f}", flush=True)
        if args.json_path:
            with open(args.json_path, "w", encoding="utf-8") as f:
                json.dump(doc, f, indent=2)
            print(f"# report -> {args.json_path}", flush=True)
        if args.smoke:
            print("io_bench service smoke: schema OK", flush=True)
        sys.exit(0)

    if args.smoke:
        args.n_images, args.size, args.workers = 48, 48, "0,2"
        args.h2d = True

    import tempfile

    sweep = [int(t) for t in str(args.workers).split(",")]
    doc = {"n_images": args.n_images, "size": args.size, "results": []}
    with tempfile.TemporaryDirectory() as workdir:
        t0 = time.perf_counter()
        generate_imgbin(workdir, args.n_images, args.size)
        doc["generated_s"] = time.perf_counter() - t0
        print(
            f"# generated {args.n_images} JPEGs ({args.size}x{args.size}) "
            f"in {doc['generated_s']:.1f}s",
            flush=True,
        )
        serial_da = None
        for w in sweep:
            da = run_instances(workdir, args.size, w)
            rate, stages = run_epoch(workdir, args.size, w, h2d=args.h2d)
            # w=0 is THE serial path; w=1 is the pool disabled by
            # count (identical code path, labeled distinctly)
            mode = "serial" if w == 0 else f"workers={w}"
            if w <= 1 and serial_da is None:
                serial_da = da
            doc["results"].append({
                "mode": mode, "img_per_sec": rate,
                "decode_augment_per_sec": da, "stages": stages,
            })
            print(f"{mode:>12}: decode+augment {da:8.1f} rows/s, "
                  f"chain {rate:8.1f} img/s", flush=True)
        if args.native:
            for t in (1, 2, 4, 0):
                try:
                    da = run_instances(workdir, args.size, 0, native=1,
                                       decode_thread=t)
                    rate, stages = run_epoch(
                        workdir, args.size, 0, native=1, decode_thread=t)
                except Exception as e:  # noqa: BLE001 - no native build
                    print(f"# native decoder unavailable: {e}", flush=True)
                    break
                label = "auto" if t == 0 else str(t)
                doc["results"].append({
                    "mode": f"native={label}", "img_per_sec": rate,
                    "decode_augment_per_sec": da, "stages": stages,
                })
                print(f"native={label:>4}: decode+augment {da:8.1f} "
                      f"rows/s, chain {rate:8.1f} img/s", flush=True)
    if serial_da:
        doc["speedup_vs_serial"] = {
            r["mode"]: r["decode_augment_per_sec"] / serial_da
            for r in doc["results"] if r["mode"].startswith("workers=")
        }
        for mode, s in doc["speedup_vs_serial"].items():
            print(f"# decode+augment speedup {mode}: {s:.2f}x vs serial",
                  flush=True)
    validate_report(doc)
    if args.json_path:
        with open(args.json_path, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=2)
        print(f"# report -> {args.json_path}", flush=True)
    if args.smoke:
        print("io_bench smoke: schema OK", flush=True)


if __name__ == "__main__":
    main()
