#!/usr/bin/env python
"""Quantized-inference smoke: train, gated export, serve A/B (QUANT=1).

The CPU-measurable acceptance of the int8 serving path
(doc/performance.md "Quantized inference"), driven the way an operator
would:

1. **train** — a real ``task=train`` subprocess fits the MNIST MLP conf
   for a few rounds and checkpoints it;
2. **export** — a real ``task=export_quant`` subprocess quantizes it
   behind the agreement gate; the verdict must be a publish with
   top-1 agreement >= 0.99 and a >= 3.5x weight-bytes reduction;
3. **serve A/B** — two in-process engines over the SAME checkpoint
   (f32 vs the exported int8 artifact) run interleaved closed-loop
   legs; the quantized leg must not regress beyond the noise band, and
   the engine's NEW ``serve_weight_bytes`` / ``serve_weight_bytes_f32``
   registry gauges must show the >= 3.5x ratio (the gauge IS the
   assertion surface, not a recomputation).

Emits one JSON verdict line on stdout (schema consumed by
``tools/perf_guard.py --bench quant_bench``); exit 0 iff every
assertion held.

Usage: python tools/quant_smoke.py [--out DIR] [--requests N]
       [--concurrency C] [--band B]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

MIN_BYTES_RATIO = 3.5
MIN_AGREEMENT = 0.99


def _run_cli(work: str, conf: str, *args: str) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.run(
        [sys.executable, "-m", "cxxnet_tpu", conf, *args],
        cwd=work, env=env, capture_output=True, text=True,
    )


def _fail(verdict: dict, msg: str) -> None:
    verdict["ok"] = False
    verdict["fail"] = msg
    print(json.dumps(verdict), flush=True)
    raise SystemExit(1)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="",
                    help="keep artifacts here (default: temp dir)")
    ap.add_argument("--rounds", type=int, default=2)
    ap.add_argument("--requests", type=int, default=60,
                    help="closed-loop requests per thread per leg")
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--rows", type=int, default=4)
    ap.add_argument("--band", type=float, default=0.2,
                    help="throughput noise band: quant must reach "
                         ">= (1-band) * f32")
    args = ap.parse_args()

    work = args.out or tempfile.mkdtemp(prefix="quant_smoke_")
    os.makedirs(work, exist_ok=True)
    from cxxnet_tpu.models import mnist_mlp_conf

    conf_text = mnist_mlp_conf(batch_size=100, synthetic=True, dev="cpu")
    conf_path = os.path.join(work, "mnist.conf")
    with open(conf_path, "w", encoding="utf-8") as f:
        f.write(conf_text)
        f.write(f"model_dir = models\nnum_round = {args.rounds}\n"
                f"max_round = {args.rounds}\nseed = 11\nsilent = 1\n")

    verdict: dict = {"ok": True, "work": work}

    # 1. train
    r = _run_cli(work, "mnist.conf", "task=train")
    if r.returncode != 0:
        _fail(verdict, f"train failed: {r.stderr[-1500:]}")
    model = os.path.join("models", f"{args.rounds:04d}.model")
    if not os.path.exists(os.path.join(work, model)):
        _fail(verdict, f"missing checkpoint {model}")

    # 2. gated export
    r = _run_cli(work, "mnist.conf", "task=export_quant",
                 f"model_in={model}", "quant_report=quant_verdict.json")
    if r.returncode != 0:
        _fail(verdict, f"export_quant exit {r.returncode}: "
                       f"{(r.stdout + r.stderr)[-1500:]}")
    with open(os.path.join(work, "quant_verdict.json"),
              encoding="utf-8") as f:
        export = json.load(f)
    verdict["export"] = export
    if not export["ok"]:
        _fail(verdict, "export rejected")
    if export["agreement"] < MIN_AGREEMENT:
        _fail(verdict, f"agreement {export['agreement']} < "
                       f"{MIN_AGREEMENT}")
    if export["bytes_ratio"] < MIN_BYTES_RATIO:
        _fail(verdict, f"artifact bytes ratio {export['bytes_ratio']:.2f}"
                       f" < {MIN_BYTES_RATIO}")

    # 3. serve A/B over the trained checkpoint, in process
    import numpy as np

    from serve_bench import closed_loop

    from cxxnet_tpu import config as cfgmod
    from cxxnet_tpu import serve
    from cxxnet_tpu.obs import registry as obs_registry

    cfg = cfgmod.parse_pairs(conf_text)
    model_abs = os.path.join(work, model)
    eng_f = serve.Engine(cfg=cfg, model_in=model_abs, max_batch_size=64,
                         queue_limit=1024)
    eng_q = serve.Engine(cfg=cfg + [("quant", "int8")],
                         model_in=model_abs, max_batch_size=64,
                         queue_limit=1024)
    try:
        if eng_q.quant_scheme != "int8":
            _fail(verdict, "quant engine did not pick up the scheme")
        if not (eng_q.model_path or "").endswith(".quant.model"):
            _fail(verdict, "quant engine did not prefer the exported "
                           "artifact")
        # the NEW gauges are the assertion surface for the 4x claim:
        # the quant engine registered last, so the registry holds its
        # weight-bytes identity
        snap = obs_registry().snapshot()
        gauge = snap["serve_weight_bytes"]["serve_weight_bytes"]
        gauge_f32 = (snap["serve_weight_bytes_f32"]
                     ["serve_weight_bytes_f32"])
        verdict["gauge"] = {"serve_weight_bytes": gauge,
                            "serve_weight_bytes_f32": gauge_f32,
                            "ratio": gauge_f32 / gauge if gauge else 0.0}
        if gauge_f32 / max(gauge, 1) < MIN_BYTES_RATIO:
            _fail(verdict, f"gauge bytes ratio "
                           f"{gauge_f32 / max(gauge, 1):.2f} < "
                           f"{MIN_BYTES_RATIO}")
        x = np.random.RandomState(0).rand(args.rows, 784).astype(
            np.float32)
        for _ in range(8):
            eng_f.predict(x)
            eng_q.predict(x)
        f_runs, q_runs = [], []
        for _ in range(2):  # interleaved best-of-2: drift hits both legs
            q_runs.append(closed_loop(eng_q, x, args.concurrency,
                                      args.requests))
            f_runs.append(closed_loop(eng_f, x, args.concurrency,
                                      args.requests))
        f32 = max(f_runs, key=lambda r: r["req_per_sec"])
        qnt = max(q_runs, key=lambda r: r["req_per_sec"])
        verdict["quant_ab"] = {
            "scheme": "int8",
            "f32": f32,
            "quant": qnt,
            "speedup": qnt["req_per_sec"] / f32["req_per_sec"],
            "bytes_ratio": verdict["gauge"]["ratio"],
            "band": args.band,
        }
        if qnt["req_per_sec"] < (1.0 - args.band) * f32["req_per_sec"]:
            _fail(verdict,
                  f"quantized throughput regressed: "
                  f"{qnt['req_per_sec']:.0f} < (1-{args.band}) * "
                  f"{f32['req_per_sec']:.0f} req/s")
    finally:
        eng_f.close()
        eng_q.close()
    print(json.dumps(verdict), flush=True)
    print(f"# quant_smoke: agreement {export['agreement']:.4f}, weight "
          f"bytes {verdict['gauge']['ratio']:.2f}x smaller, serve "
          f"{f32['req_per_sec']:.0f} -> {qnt['req_per_sec']:.0f} req/s "
          f"(speedup {verdict['quant_ab']['speedup']:.2f})",
          file=sys.stderr, flush=True)


if __name__ == "__main__":
    main()
